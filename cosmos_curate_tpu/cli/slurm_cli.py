"""`cosmos-curate-tpu slurm` — submit and manage pipeline jobs on Slurm.

Equivalent capability of the reference's slurm CLI
(cosmos_curate/client/slurm_cli/slurm.py:244-564 + scripts/onto_slurm.py +
prometheus_service_discovery.py): sbatch generation, local or SSH remote
submission with job-id parsing, job status/log/cancel management, and
Prometheus service-discovery file generation so a fleet dashboard scrapes
per-node engine metrics.

TPU-flavored topology: the reference runs node 0 as a Ray head plus driver
and the rest as Ray workers; here every node runs the same SPMD program
under ``jax.distributed`` (cosmos_curate_tpu/parallel/distributed.py), with
deterministic task partitioning and convergent resume across nodes — node 0
is only special as the coordinator address.

Subcommands:
  submit   generate an sbatch script; print, write, or submit it
           (``--remote-host user@host`` scp+sbatch's it over SSH)
  status   squeue/sacct for a job id
  logs     tail the job's output file
  cancel   scancel a job id
  prom-sd  write a Prometheus HTTP-SD JSON from a hostfile
"""

from __future__ import annotations

import argparse
import json
import re
import os
import shlex
import subprocess
from pathlib import Path

_SBATCH_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={job_name}
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task={cpus_per_task}
#SBATCH --time={time_limit}
#SBATCH --output={log_dir}/%x-%j.out
{extra_directives}
set -euo pipefail

# coordinator = first node in the allocation (jax.distributed convention);
# CURATE_NODE_RANK is resolved per task by srun via SLURM_NODEID.
COORD=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
export CURATE_COORDINATOR_ADDRESS="$COORD:{coordinator_port}"
export CURATE_NUM_NODES="$SLURM_JOB_NUM_NODES"
{env_exports}
{prom_sd_step}
{engine_plane_exports}# srun exports the environment; no nested shell, so arbitrary quoting in
# the command survives verbatim. Node rank is read from SLURM_NODEID by
# cosmos_curate_tpu.parallel.distributed in each task.
{srun_step}
{merge_step}"""

_SRUN_DEFAULT = "srun --kill-on-bad-exit=1 {python} -m cosmos_curate_tpu.cli.main {command}"
# engine-plane topology: node 0 runs the driver (the pipeline command,
# carried shlex-quoted in CURATE_DRIVER_CMD and re-parsed by eval); every
# other node runs an agent that joins the driver's CPU-stage pools
_SRUN_ENGINE_PLANE = (
    "srun --kill-on-bad-exit=1 bash -c 'if [ \"$SLURM_NODEID\" = 0 ]; then "
    # the driver is a SINGLE-node pipeline whose extra capacity arrives via
    # agents — the jax.distributed/partition contract must not see N nodes
    # (it would block in initialize waiting for peers that run agents, and
    # partition away (N-1)/N of the input)
    "export CURATE_NUM_NODES=1; unset CURATE_COORDINATOR_ADDRESS; "
    'eval "exec {python} -m cosmos_curate_tpu.cli.main $CURATE_DRIVER_CMD"; else '
    "unset CURATE_ENGINE_DRIVER_PORT; "
    "exec {python} -m cosmos_curate_tpu.engine.remote_agent "
    '--driver "$COORD:{engine_port}"; fi\''
)


def parse_job_id(sbatch_output: str) -> str:
    """'Submitted batch job 12345' -> '12345' (reference slurm.py:302)."""
    m = re.search(r"Submitted batch job (\d+)", sbatch_output)
    if not m:
        raise ValueError(f"cannot parse job id from sbatch output: {sbatch_output!r}")
    return m.group(1)


def write_prometheus_sd(
    path: Path,
    hosts: list[str],
    *,
    port: int,
    job_id: str = "",
    job_name: str = "",
    job_user: str = "",
) -> None:
    """Prometheus HTTP-SD / file-SD JSON listing every node's metrics
    endpoint (reference prometheus_service_discovery.py:53-71; our engine
    serves the `pipeline_*` gauges on --metrics-port)."""
    data = [
        {
            "labels": {
                "job": "cosmos-curate-tpu",
                "slurm_job_user": job_user,
                "slurm_job_id": job_id,
                "slurm_job_name": job_name,
            },
            "targets": [f"{h}:{port}" for h in hosts if h],
        }
    ]
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(data, indent=2))


def render_sbatch(args: argparse.Namespace, command: list[str]) -> str:
    extra = []
    if args.partition:
        extra.append(f"#SBATCH --partition={args.partition}")
    if args.account:
        extra.append(f"#SBATCH --account={args.account}")
    env_exports = "\n".join(f"export {shlex.quote(e)}" for e in args.env)
    merge_step = ""
    if args.merge_output:
        merge_step = (
            "\n# all partitions done: fold per-node summaries into one\n"
            f"python -m cosmos_curate_tpu.cli.main local merge-summaries "
            f"--output-path {shlex.quote(args.merge_output)}\n"
        )
    prom_sd_step = ""
    if args.prom_sd_file:
        # monitoring registration must never kill the compute job (the
        # template runs under set -e), hence the || warning; the nodes temp
        # file is removed either way
        prom_sd_step = (
            "# register every node with the metrics scraper before the run\n"
            'NODES_FILE=$(mktemp)\n'
            'scontrol show hostnames "$SLURM_JOB_NODELIST" > "$NODES_FILE"\n'
            f"python -m cosmos_curate_tpu.cli.main slurm prom-sd "
            f"--path {shlex.quote(args.prom_sd_file)} "
            f'--hostfile "$NODES_FILE" '
            f"--port {args.metrics_port} "
            '--job-id "$SLURM_JOB_ID" --job-name "$SLURM_JOB_NAME" --job-user "$USER" '
            '|| echo "warning: prometheus service-discovery registration failed" >&2\n'
            'rm -f "$NODES_FILE"\n'
        )
    quoted_command = " ".join(shlex.quote(c) for c in command)
    engine_plane_exports = ""
    if getattr(args, "engine_plane", False):
        engine_plane_exports = (
            "# cross-node engine plane: node 0 drives, other nodes run agents\n"
            "export CURATE_ENGINE_TOKEN=\"${CURATE_ENGINE_TOKEN:-"
            "$(head -c16 /dev/urandom | od -An -tx1 | tr -d ' \\n')}\"\n"
            f"export CURATE_ENGINE_DRIVER_PORT={args.engine_port}\n"
            'export CURATE_ENGINE_WAIT_NODES="$((SLURM_JOB_NUM_NODES - 1))"\n'
            f"export CURATE_DRIVER_CMD={shlex.quote(quoted_command)}\n"
        )
        srun_step = _SRUN_ENGINE_PLANE.format(
            python="python", engine_port=args.engine_port
        )
    else:
        srun_step = _SRUN_DEFAULT.format(python="python", command=quoted_command)
    return _SBATCH_TEMPLATE.format(
        merge_step=merge_step,
        prom_sd_step=prom_sd_step,
        job_name=args.job_name,
        nodes=args.nodes,
        cpus_per_task=args.cpus_per_task,
        time_limit=args.time_limit,
        log_dir=args.log_dir,
        extra_directives="\n".join(extra),
        coordinator_port=args.coordinator_port,
        env_exports=env_exports,
        engine_plane_exports=engine_plane_exports,
        srun_step=srun_step,
    )


def _run(cmd: list[str]) -> subprocess.CompletedProcess:
    return subprocess.run(cmd, capture_output=True, text=True)


def _remote(host: str, cmd: list[str]) -> subprocess.CompletedProcess:
    return _run(["ssh", "-o", "BatchMode=yes", host, shlex.join(cmd)])


# -- commands --------------------------------------------------------------


def _cmd_submit(args: argparse.Namespace) -> int:
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print(
            "error: pass the pipeline command after '--', e.g. "
            "slurm submit --nodes 4 -- local split --config run.yaml"
        )
        return 2
    script = render_sbatch(args, command)
    if args.output:
        Path(args.output).write_text(script)
        print(f"wrote {args.output}")
    else:
        print(script)
    if not args.submit:
        return 0
    if args.output:
        target = args.output
    else:
        # Unpredictable per-invocation name: a fixed path in world-writable
        # /tmp is clobbered by concurrent submitters and invites symlink
        # pre-creation races on shared login nodes.
        import tempfile

        fd, target = tempfile.mkstemp(prefix="cosmos_curate_tpu_", suffix=".sbatch")
        with os.fdopen(fd, "w") as fh:
            fh.write(script)
    if args.remote_host:
        mk = _remote(args.remote_host, ["mktemp", "-t", "cosmos_curate_tpu_XXXXXX.sbatch"])
        if mk.returncode != 0:
            print(mk.stderr)
            return mk.returncode
        remote_path = mk.stdout.strip()
        scp = _run(["scp", "-o", "BatchMode=yes", target, f"{args.remote_host}:{remote_path}"])
        if scp.returncode != 0:
            print(scp.stderr)
            return scp.returncode
        result = _remote(args.remote_host, ["sbatch", remote_path])
    else:
        result = _run(["sbatch", target])
    out = result.stdout or result.stderr
    print(out.strip())
    if result.returncode == 0:
        try:
            print(f"job-id: {parse_job_id(out)}")
        except ValueError:
            pass
    return result.returncode


def _cmd_status(args: argparse.Namespace) -> int:
    cmd = ["squeue", "-j", args.job_id, "-o", "%i %j %T %M %D %R"]
    result = _remote(args.remote_host, cmd) if args.remote_host else _run(cmd)
    out = (result.stdout or "").strip()
    # a finished job drops out of squeue; fall back to accounting
    if result.returncode != 0 or len(out.splitlines()) < 2:
        cmd = ["sacct", "-j", args.job_id, "--format=JobID,JobName,State,Elapsed", "-n"]
        result = _remote(args.remote_host, cmd) if args.remote_host else _run(cmd)
        out = (result.stdout or result.stderr).strip()
    print(out)
    return result.returncode


def _cmd_logs(args: argparse.Namespace) -> int:
    log = str(Path(args.log_dir) / f"{args.job_name}-{args.job_id}.out")
    cmd = ["tail", "-n", str(args.lines), log]
    if args.follow:
        cmd.insert(1, "-f")
        # follow streams to the terminal; no capture
        if args.remote_host:
            return subprocess.run(
                ["ssh", "-o", "BatchMode=yes", args.remote_host, shlex.join(cmd)]
            ).returncode
        return subprocess.run(cmd).returncode
    result = _remote(args.remote_host, cmd) if args.remote_host else _run(cmd)
    print(result.stdout or result.stderr)
    return result.returncode


def _cmd_cancel(args: argparse.Namespace) -> int:
    cmd = ["scancel", args.job_id]
    result = _remote(args.remote_host, cmd) if args.remote_host else _run(cmd)
    if result.returncode == 0:
        print(f"cancelled {args.job_id}")
    else:
        print(result.stderr.strip())
    return result.returncode


def _cmd_prom_sd(args: argparse.Namespace) -> int:
    hosts = [
        line.strip()
        for line in Path(args.hostfile).read_text().splitlines()
        if line.strip()
    ]
    write_prometheus_sd(
        Path(args.path),
        hosts,
        port=args.port,
        job_id=args.job_id,
        job_name=args.job_name,
        job_user=args.job_user,
    )
    print(f"wrote {args.path} ({len(hosts)} targets)")
    return 0


def register(sub: argparse._SubParsersAction) -> None:
    slurm = sub.add_parser("slurm", help="submit/manage pipeline jobs on Slurm")
    ssub = slurm.add_subparsers(dest="slurm_command", metavar="subcommand", required=True)

    sb = ssub.add_parser("submit", help="generate/submit an sbatch script")
    sb.add_argument("--job-name", default="cosmos-curate-tpu")
    sb.add_argument("--nodes", type=int, default=1)
    sb.add_argument("--cpus-per-task", type=int, default=96)
    sb.add_argument("--time-limit", default="04:00:00")
    sb.add_argument("--log-dir", default="slurm_logs")
    sb.add_argument("--partition", default="")
    sb.add_argument("--account", default="")
    sb.add_argument("--coordinator-port", type=int, default=8476)
    sb.add_argument(
        "--engine-plane",
        action="store_true",
        help="node 0 drives the streaming engine; other nodes run "
        "engine.remote_agent workers joined over the cross-node data plane",
    )
    sb.add_argument("--engine-port", type=int, default=8478)
    sb.add_argument("--env", action="append", default=[], metavar="K=V")
    sb.add_argument(
        "--merge-output",
        default="",
        metavar="PATH",
        help="after all nodes finish, merge per-node summaries under PATH "
        "into summary-merged.json (runs once, on the batch host)",
    )
    sb.add_argument(
        "--prom-sd-file",
        default="",
        metavar="PATH",
        help="write a Prometheus service-discovery JSON for the allocation's "
        "nodes at job start",
    )
    sb.add_argument("--metrics-port", type=int, default=9002)
    sb.add_argument("--output", default="", help="write script here instead of printing")
    sb.add_argument("--submit", action="store_true", help="sbatch the generated script")
    sb.add_argument(
        "--remote-host", default="", metavar="USER@HOST",
        help="scp the script to this host and sbatch there over SSH",
    )
    sb.add_argument("command", nargs=argparse.REMAINDER, help="cosmos-curate-tpu subcommand")
    sb.set_defaults(func=_cmd_submit)

    st = ssub.add_parser("status", help="squeue/sacct for a job")
    st.add_argument("--job-id", required=True)
    st.add_argument("--remote-host", default="")
    st.set_defaults(func=_cmd_status)

    lg = ssub.add_parser("logs", help="show the job's output log")
    lg.add_argument("--job-id", required=True)
    lg.add_argument("--job-name", default="cosmos-curate-tpu")
    lg.add_argument("--log-dir", default="slurm_logs")
    lg.add_argument("--lines", type=int, default=100)
    lg.add_argument("--follow", action="store_true")
    lg.add_argument("--remote-host", default="")
    lg.set_defaults(func=_cmd_logs)

    ca = ssub.add_parser("cancel", help="scancel a job")
    ca.add_argument("--job-id", required=True)
    ca.add_argument("--remote-host", default="")
    ca.set_defaults(func=_cmd_cancel)

    pd = ssub.add_parser("prom-sd", help="write Prometheus service-discovery JSON")
    pd.add_argument("--path", required=True)
    pd.add_argument("--hostfile", required=True)
    pd.add_argument("--port", type=int, default=9002)
    pd.add_argument("--job-id", default="")
    pd.add_argument("--job-name", default="")
    pd.add_argument("--job-user", default="")
    pd.set_defaults(func=_cmd_prom_sd)
