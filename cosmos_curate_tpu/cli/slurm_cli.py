"""`cosmos-curate-tpu slurm` — generate/submit sbatch scripts for TPU pods.

Equivalent capability of the reference's slurm CLI
(cosmos_curate/client/slurm_cli/slurm.py + scripts/onto_slurm.py — node 0
runs the driver, others join the cluster). TPU-flavored: every node runs the
same program under `jax.distributed` (SPMD), with node 0 also running the
pipeline driver; coordinator discovery via the Slurm nodelist.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
from pathlib import Path

_SBATCH_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={job_name}
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task={cpus_per_task}
#SBATCH --time={time_limit}
#SBATCH --output={log_dir}/%x-%j.out
{extra_directives}
set -euo pipefail

# coordinator = first node in the allocation (jax.distributed convention);
# CURATE_NODE_RANK is resolved per task by srun via SLURM_NODEID.
COORD=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
export CURATE_COORDINATOR_ADDRESS="$COORD:{coordinator_port}"
export CURATE_NUM_NODES="$SLURM_JOB_NUM_NODES"
{env_exports}

# srun exports the environment; no nested shell, so arbitrary quoting in
# the command survives verbatim. Node rank is read from SLURM_NODEID by
# cosmos_curate_tpu.parallel.distributed in each task.
srun --kill-on-bad-exit=1 {python} -m cosmos_curate_tpu.cli.main {command}
{merge_step}"""


def register(sub: argparse._SubParsersAction) -> None:
    slurm = sub.add_parser("slurm", help="generate/submit sbatch for a TPU pod")
    slurm.add_argument("--job-name", default="cosmos-curate-tpu")
    slurm.add_argument("--nodes", type=int, default=1)
    slurm.add_argument("--cpus-per-task", type=int, default=96)
    slurm.add_argument("--time-limit", default="04:00:00")
    slurm.add_argument("--log-dir", default="slurm_logs")
    slurm.add_argument("--partition", default="")
    slurm.add_argument("--account", default="")
    slurm.add_argument("--coordinator-port", type=int, default=8476)
    slurm.add_argument("--env", action="append", default=[], metavar="K=V")
    slurm.add_argument(
        "--merge-output",
        default="",
        metavar="PATH",
        help="after all nodes finish, merge per-node summaries under PATH "
        "into summary-merged.json (runs once, on the batch host)",
    )
    slurm.add_argument("--output", default="", help="write script here instead of submitting")
    slurm.add_argument("--submit", action="store_true", help="sbatch the generated script")
    slurm.add_argument("command", nargs=argparse.REMAINDER, help="cosmos-curate-tpu subcommand to run")
    slurm.set_defaults(func=_cmd_slurm)


def _cmd_slurm(args: argparse.Namespace) -> int:
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("error: pass the pipeline command after '--', e.g. "
              "slurm --nodes 4 -- local split --config run.yaml")
        return 2
    extra = []
    if args.partition:
        extra.append(f"#SBATCH --partition={args.partition}")
    if args.account:
        extra.append(f"#SBATCH --account={args.account}")
    env_exports = "\n".join(f"export {shlex.quote(e)}" for e in args.env)
    merge_step = ""
    if args.merge_output:
        merge_step = (
            "\n# all partitions done: fold per-node summaries into one\n"
            f"python -m cosmos_curate_tpu.cli.main local merge-summaries "
            f"--output-path {shlex.quote(args.merge_output)}\n"
        )
    script = _SBATCH_TEMPLATE.format(
        merge_step=merge_step,
        job_name=args.job_name,
        nodes=args.nodes,
        cpus_per_task=args.cpus_per_task,
        time_limit=args.time_limit,
        log_dir=args.log_dir,
        extra_directives="\n".join(extra),
        coordinator_port=args.coordinator_port,
        env_exports=env_exports,
        python="python",
        command=" ".join(shlex.quote(c) for c in command),
    )
    if args.output:
        Path(args.output).write_text(script)
        print(f"wrote {args.output}")
    else:
        print(script)
    if args.submit:
        target = args.output or "/tmp/cosmos_curate_tpu.sbatch"
        if not args.output:
            Path(target).write_text(script)
        result = subprocess.run(["sbatch", target], capture_output=True, text=True)
        print(result.stdout or result.stderr)
        return result.returncode
    return 0
