"""`cosmos-curate-tpu view` — local web viewer for curated output.

Equivalent capability of the reference's clip viewer
(cosmos_curate/client/view_cli/clip_viewer.py:316): browse clips, captions
and scores from a split output directory in the browser.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def register(sub: argparse._SubParsersAction) -> None:
    view = sub.add_parser("view", help="browse curated clips in a browser")
    view.add_argument("--input-path", required=True, help="split output root")
    view.add_argument("--host", default="127.0.0.1")
    view.add_argument("--port", type=int, default=8081)
    view.set_defaults(func=_cmd_view)


_PAGE = """<!DOCTYPE html>
<html><head><title>cosmos-curate-tpu viewer</title>
<style>
body {{ font-family: sans-serif; margin: 2rem; background: #111; color: #eee; }}
.clip {{ display: inline-block; margin: 1rem; padding: 1rem; background: #1c1c1c;
        border-radius: 8px; vertical-align: top; width: 340px; }}
video {{ width: 320px; border-radius: 4px; }}
.meta {{ font-size: 0.8rem; color: #aaa; white-space: pre-wrap; }}
.caption {{ font-size: 0.9rem; margin-top: 0.5rem; }}
</style></head>
<body><h1>Curated clips ({count})</h1>{clips}</body></html>
"""

_CLIP = """<div class="clip">
<video controls src="/clips/{uuid}.mp4"></video>
<div class="caption">{caption}</div>
<div class="meta">span {span_start:.1f}-{span_end:.1f}s | motion {motion} | aesthetic {aesthetic}{filtered}</div>
</div>"""


def _render_index(root: Path) -> str:
    import html

    cards = []
    for meta_path in sorted((root / "metas" / "v0").glob("*.json")):
        meta = json.loads(meta_path.read_text())
        captions = [
            c for w in meta.get("windows", []) for c in (w.get("captions") or {}).values() if c
        ]
        # captions are model output over untrusted video: escape everything
        cards.append(
            _CLIP.format(
                uuid=html.escape(str(meta["uuid"])),
                caption=(html.escape(captions[0]) if captions else "<i>no caption</i>"),
                span_start=meta["span_start"],
                span_end=meta["span_end"],
                motion=_fmt(meta.get("motion_score_global")),
                aesthetic=_fmt(meta.get("aesthetic_score")),
                filtered=(
                    f" | FILTERED: {html.escape(str(meta['filtered_by']))}"
                    if meta.get("filtered_by")
                    else ""
                ),
            )
        )
    return _PAGE.format(count=len(cards), clips="\n".join(cards))


def _fmt(v) -> str:
    return f"{v:.4f}" if isinstance(v, (int, float)) else "-"


def _cmd_view(args: argparse.Namespace) -> int:
    from aiohttp import web

    root = Path(args.input_path)
    if not (root / "metas" / "v0").exists():
        print(f"error: {root} does not look like a split output (no metas/v0)")
        return 2

    async def index(request: web.Request) -> web.Response:
        return web.Response(text=_render_index(root), content_type="text/html")

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_static("/clips", str(root / "clips"))
    print(f"viewer at http://{args.host}:{args.port}/")
    web.run_app(app, host=args.host, port=args.port)
    return 0
