"""Background index compaction: fold pending fragments, rebalance skewed
clusters, refresh stale centroids, publish a new manifest generation.

The write half of the index-server read path (dedup/index_server.py).
Ingest keeps the index append-only — ``ClipWriterStage`` writes
``pending/`` fragments and ``consolidate_index`` routes them — which over
time skews clusters (hot content piles into few lists) and stales
centroids (the mean drifts away from the stored vector). Compaction fixes
both WITHOUT stopping reads:

1. **Fold pending** (duplicate-free): the pending fragment set is
   snapshotted at entry; rows are provenance- and model-gated exactly like
   ``consolidate_index``, deduplicated against the indexed ids AND within
   the fold (a re-run of a crashed fold cannot double-ingest), routed to
   the current centroids, and appended as cluster fragments.
2. **Rebalance skew**: clusters holding more than ``rebalance_factor`` ×
   the mean row count are split in two by a local k-means
   (``kmeans_fit(members, 2)``), bounding worst-case probe cost.
3. **Refresh centroids**: every cluster's centroid is recomputed as the
   normalized mean of its members; the manifest pins the refreshed set as
   ``centroids-<gen>.npy`` (live ``centroids.npy``/``meta.json`` are
   updated too, so batch readers and future ``add`` routing see it).
4. **Publish atomically**: a new ``manifests/gen-<N>.json`` referencing
   the exact post-compaction fragment set, then the ``MANIFEST.json``
   pointer flip. Readers adopt between requests; nothing is published
   unless something actually changed (fold, split, or centroid drift
   above ``drift_tol``).

Fragments referenced only by superseded generations are **not** deleted
at publish — in-flight snapshot readers still hold them. They are listed
in the new manifest's ``superseded`` field and reclaimed by
:func:`gc_superseded` (the server's drain callback) or :func:`gc_index`
(the ``index compact --gc`` full sweep). Until GC runs, live (manifest-
less) readers may see a row in both its old and new fragment — benign:
``score_shards`` deduplicates hits by clip id.

Single-writer contract: one compactor per index root at a time (the
in-service :class:`CompactionThread`, or the CLI while no service runs).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from cosmos_curate_tpu.dedup.corpus_index import _record_index_ops
from cosmos_curate_tpu.dedup.index_store import (
    IndexStore,
    allow_random_provenance,
    normalize_rows,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_REBALANCE_FACTOR = 4.0
DEFAULT_MIN_SPLIT_ROWS = 16
DEFAULT_DRIFT_TOL = 1e-3


def compact_index(
    root: str,
    *,
    mesh=None,
    fold_pending: bool = True,
    rebalance: bool = True,
    rebalance_factor: float = DEFAULT_REBALANCE_FACTOR,
    min_split_rows: int = DEFAULT_MIN_SPLIT_ROWS,
    refresh_centroids: bool = True,
    drift_tol: float = DEFAULT_DRIFT_TOL,
    force: bool = False,
    gc: bool = False,
    metrics_name: str = "compaction",
) -> dict:
    """One compaction pass over the index at ``root``. Returns a report;
    ``report["published"]`` is False when nothing needed doing (no pending
    rows, no skew, centroid drift under ``drift_tol``, and not ``force``).
    """
    t0 = time.monotonic()
    store = IndexStore(root)
    if not store.exists():
        raise FileNotFoundError(f"no corpus index at {root} (run `index build` first)")
    base_gen = store.current_generation()
    base_manifest = store.read_manifest(base_gen)
    centroids = np.asarray(store.load_centroids(base_manifest.get("centroids") or None), np.float32)
    meta = dict(base_manifest.get("meta") or store.load_meta())
    report = {
        "index_path": store.root,
        "base_generation": base_gen,
        "published": False,
        "generation": base_gen,
        "folded": 0,
        "absorbed": 0,  # live post-publish `add` fragments pulled into the manifest
        "skipped_random": 0,
        "model_dropped": 0,
        "duplicates_dropped": 0,
        "clusters_split": 0,
        "rows_moved": 0,
        "centroid_drift": 0.0,
        "pending_cleared": 0,
        "gc_deleted": 0,
    }

    # -- load the pinned cluster contents (compaction is the one pass that
    # legitimately reads the whole index — it is the maintenance walk)
    clusters: dict[int, tuple[list[str], np.ndarray]] = {}
    for cid_s, info in (base_manifest.get("clusters") or {}).items():
        ids, vecs = store.read_fragments(list(info.get("fragments") or []))
        if ids:
            clusters[int(cid_s)] = (ids, vecs)
    indexed_ids = {u for ids, _v in clusters.values() for u in ids}
    changed: set[int] = set()  # clusters whose fragment set must be rewritten

    # Absorb live fragments the base manifest does NOT pin: rows appended
    # by ``CorpusIndex.add`` / `index consolidate` AFTER the base
    # generation was published land directly under clusters/ and would
    # otherwise never enter any future manifest (and a later GC would
    # delete them). Superseded leftovers of older generations surface here
    # too — their rows are already in ``indexed_ids`` and dedup away, so
    # absorbing is always safe.
    if base_gen > 0:
        pinned_frags = {
            f
            for info in (base_manifest.get("clusters") or {}).values()
            for f in (info.get("fragments") or [])
        }
        for cid in sorted(store.cluster_fragment_counts()):
            extras = [
                rel
                for rel, _sz in store.fragment_info(f"clusters/{store.cluster_dir(cid)}")
                if rel not in pinned_frags
            ]
            if not extras:
                continue
            e_ids, e_vecs = store.read_fragments(extras)
            novel = []
            for i, u in enumerate(e_ids):
                if u not in indexed_ids:
                    novel.append(i)
                    indexed_ids.add(u)
            if not novel:
                continue
            old_ids, old_vecs = clusters.get(
                cid, ([], np.zeros((0, e_vecs.shape[1]), np.float32))
            )
            clusters[cid] = (
                list(old_ids) + [e_ids[i] for i in novel],
                np.concatenate([old_vecs, e_vecs[novel]]) if len(old_ids) else e_vecs[novel],
            )
            changed.add(cid)
            report["absorbed"] += len(novel)

    # -- 1. fold pending (duplicate-free) ------------------------------------
    pending_paths = store.list_pending() if fold_pending else []
    pending_rel = [store._relpath(p) for p in pending_paths]
    if pending_paths:
        p_ids, p_vecs, p_models, p_provs = store.read_pending()
        keep = list(range(len(p_ids)))
        if not allow_random_provenance():
            refused = [i for i in keep if p_provs[i] == "random"]
            report["skipped_random"] = len(refused)
            keep = [i for i in keep if p_provs[i] != "random"]
        model = meta.get("model") or next((m for m in p_models if m), "")
        if model:
            dropped = [i for i in keep if p_models[i] not in (model, "")]
            if dropped:
                logger.warning(
                    "compaction: dropping %d pending rows from other embedding "
                    "models (index model: %s)", len(dropped), model,
                )
                report["model_dropped"] = len(dropped)
            keep = [i for i in keep if p_models[i] in (model, "")]
        seen_fold: set[str] = set()
        fold_rows: list[int] = []
        for i in keep:
            if p_ids[i] in indexed_ids or p_ids[i] in seen_fold:
                report["duplicates_dropped"] += 1
                continue
            seen_fold.add(p_ids[i])
            fold_rows.append(i)
        if fold_rows:
            f_ids = [p_ids[i] for i in fold_rows]
            f_vecs = normalize_rows(p_vecs[fold_rows])
            assign = np.argmax(f_vecs @ centroids.T, axis=1)
            for cid in np.unique(assign):
                members = np.flatnonzero(assign == cid)
                old_ids, old_vecs = clusters.get(int(cid), ([], np.zeros((0, f_vecs.shape[1]), np.float32)))
                clusters[int(cid)] = (
                    list(old_ids) + [f_ids[m] for m in members],
                    np.concatenate([old_vecs, f_vecs[members]]) if len(old_ids) else f_vecs[members],
                )
                changed.add(int(cid))
            indexed_ids.update(f_ids)
            report["folded"] = len(fold_rows)

    # -- 2. rebalance skewed clusters ----------------------------------------
    new_centroids: dict[int, np.ndarray] = {}
    if rebalance and clusters:
        sizes = {cid: len(ids) for cid, (ids, _v) in clusters.items()}
        mean_rows = sum(sizes.values()) / max(1, len(sizes))
        next_cid = max(max(clusters), centroids.shape[0] - 1) + 1
        for cid in sorted(clusters):
            ids, vecs = clusters[cid]
            if len(ids) < max(min_split_rows, int(rebalance_factor * mean_rows)):
                continue
            from cosmos_curate_tpu.dedup.kmeans import kmeans_fit

            subc, sub_assign = kmeans_fit(vecs, 2, iters=10, seed=cid, mesh=mesh)
            a = np.flatnonzero(sub_assign == 0)
            b = np.flatnonzero(sub_assign == 1)
            if len(a) == 0 or len(b) == 0:
                continue  # degenerate split: all rows are one point
            clusters[cid] = ([ids[m] for m in a], vecs[a])
            clusters[next_cid] = ([ids[m] for m in b], vecs[b])
            new_centroids[cid] = subc[0]
            new_centroids[next_cid] = subc[1]
            changed.add(cid)
            changed.add(next_cid)
            report["clusters_split"] += 1
            report["rows_moved"] += len(b)
            logger.info(
                "compaction: split cluster %d (%d rows) -> %d + %d",
                cid, len(ids), len(a), len(b),
            )
            next_cid += 1

    # -- 3. refresh centroids ------------------------------------------------
    k_new = max(max(clusters) + 1 if clusters else 1, centroids.shape[0])
    refreshed = np.zeros((k_new, centroids.shape[1]), np.float32)
    refreshed[: centroids.shape[0]] = centroids
    drift = 0.0
    for cid, (ids, vecs) in clusters.items():
        if cid in new_centroids:
            refreshed[cid] = new_centroids[cid]
            continue
        if refresh_centroids and len(ids):
            fresh = normalize_rows(vecs.mean(axis=0, keepdims=True))[0]
            if cid < centroids.shape[0]:
                drift = max(drift, float(1.0 - fresh @ centroids[cid]))
            refreshed[cid] = fresh
    report["centroid_drift"] = round(drift, 6)

    if not (
        force
        or report["folded"]
        or report["absorbed"]
        or report["clusters_split"]
        or (refresh_centroids and drift > drift_tol)
    ):
        # nothing changed in the index — no new generation. Pending
        # fragments whose rows were ALL consumed anyway (duplicates of
        # indexed ids, or refused random-provenance rows — logged above)
        # still clear, or every later pass would re-read them forever.
        consumed = (
            report["duplicates_dropped"] + report["skipped_random"]
            + report["model_dropped"]
        )
        if pending_rel and consumed > 0:
            report["pending_cleared"] = store.delete_fragments(pending_rel)
        return report

    # -- 4. write fragments + publish the generation -------------------------
    gen = max([base_gen] + store.list_manifests()) + 1
    manifest_clusters: dict[str, dict] = {}
    base_clusters = base_manifest.get("clusters") or {}
    for cid in sorted(clusters):
        ids, vecs = clusters[cid]
        if not ids:
            continue
        if cid in changed or str(cid) not in base_clusters:
            # consolidate to ONE fragment per touched cluster (that is the
            # "compaction": many append fragments fold into one read)
            path = store.append_cluster(cid, ids, vecs)
            frags = [store._relpath(path)]
            nbytes = sum(sz for rel, sz in store.fragment_info(
                f"clusters/{store.cluster_dir(cid)}"
            ) if rel in frags)
        else:
            info = base_clusters[str(cid)]
            frags = list(info.get("fragments") or [])
            nbytes = int(info.get("bytes", 0))
        manifest_clusters[str(cid)] = {
            "fragments": frags,
            "rows": len(ids),
            "bytes": nbytes,
        }
    cent_rel = store.save_centroids(refreshed, generation=gen)
    store.save_centroids(refreshed)  # live copy: batch readers + add routing
    num_vectors = sum(int(c["rows"]) for c in manifest_clusters.values())
    meta.update({"k": int(refreshed.shape[0]), "num_vectors": num_vectors})
    store.save_meta(meta)
    meta = store.load_meta()  # re-read: save_meta stamps backend
    new_frag_set = {
        f for c in manifest_clusters.values() for f in c["fragments"]
    }
    superseded = sorted(
        {
            f
            for c in base_clusters.values()
            for f in (c.get("fragments") or [])
            if f not in new_frag_set
        }
    )
    manifest = {
        "generation": gen,
        "centroids": cent_rel,
        "meta": meta,
        "clusters": manifest_clusters,
        "superseded": superseded,
        "base_generation": base_gen,
    }
    store.publish_manifest(manifest)
    report["published"] = True
    report["generation"] = gen
    # pending cleared ONLY for the fragments this pass read — fragments the
    # writer appended meanwhile stay for the next pass
    if pending_rel:
        report["pending_cleared"] = store.delete_fragments(pending_rel)
    if gc:
        report["gc_deleted"] = gc_index(store)
    wall = time.monotonic() - t0
    _record_index_ops(metrics_name, adds=report["folded"], add_s=wall)
    _record_compaction(metrics_name, gen, wall)
    logger.info(
        "compaction published generation %d: folded %d, split %d cluster(s), "
        "drift %.4f, %d vectors (%.2fs)",
        gen, report["folded"], report["clusters_split"], drift, num_vectors, wall,
    )
    return report


# ---------------------------------------------------------------------------
# garbage collection


def gc_superseded(store: IndexStore, old_manifest: dict, current_manifest: dict) -> int:
    """Drain-time GC (index_server snapshot release): delete fragments the
    superseded manifest referenced that the current one does not."""
    keep = {
        f
        for c in (current_manifest.get("clusters") or {}).values()
        for f in (c.get("fragments") or [])
    }
    victims = [
        f
        for c in (old_manifest.get("clusters") or {}).values()
        for f in (c.get("fragments") or [])
        if f not in keep
    ]
    n = store.delete_fragments(victims)
    old_gen = int(old_manifest.get("generation", 0))
    if old_gen > 0:
        store.delete_manifest(old_gen)
    if n:
        logger.info("gc: reclaimed %d fragment(s) of generation %d", n, old_gen)
    return n


def gc_index(store: IndexStore) -> int:
    """Full sweep (``index compact --gc``; safe only with no snapshot
    readers): delete every cluster fragment the CURRENT manifest does not
    reference, plus superseded manifest files."""
    current_gen = store.current_generation()
    if current_gen <= 0:
        return 0  # live view: everything on disk IS the index
    manifest = store.read_manifest(current_gen)
    keep = {
        f
        for c in (manifest.get("clusters") or {}).values()
        for f in (c.get("fragments") or [])
    }
    victims: list[str] = []
    for cid in store.cluster_fragment_counts():
        for rel, _sz in store.fragment_info(f"clusters/{store.cluster_dir(cid)}"):
            if rel not in keep:
                victims.append(rel)
    n = store.delete_fragments(victims)
    for gen in store.list_manifests():
        if gen < current_gen:
            store.delete_manifest(gen)
    return n


# ---------------------------------------------------------------------------
# background thread


class CompactionThread(threading.Thread):
    """In-service compactor: one pass every ``interval_s``, publishing only
    when something changed. The paired :class:`~cosmos_curate_tpu.dedup.
    index_server.IndexServer` adopts new generations between batches; its
    drain callback (``gc_drained=True``) reclaims superseded fragments."""

    def __init__(
        self,
        root: str,
        *,
        interval_s: float = 30.0,
        mesh=None,
        metrics_name: str = "compaction",
        **compact_kw,
    ) -> None:
        super().__init__(name="index-compactor", daemon=True)
        self.root = root
        self.interval_s = interval_s
        self.mesh = mesh
        self.metrics_name = metrics_name
        self.compact_kw = compact_kw
        self._stop_event = threading.Event()
        self.passes = 0
        self.last_report: dict | None = None

    def run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            self.run_once()

    def run_once(self) -> dict | None:
        try:
            self.last_report = compact_index(
                self.root, mesh=self.mesh, metrics_name=self.metrics_name,
                **self.compact_kw,
            )
            self.passes += 1
            return self.last_report
        except Exception:
            logger.exception("compaction pass failed; index unchanged")
            return None

    def stop(self, timeout: float = 30.0) -> None:
        self._stop_event.set()
        self.join(timeout=timeout)


def _record_compaction(name: str, generation: int, wall_s: float) -> None:
    try:
        from cosmos_curate_tpu.observability.stage_timer import record_search

        record_search(name, compactions=1, compaction_s=wall_s, generation=generation)
    except Exception:
        logger.debug("compaction metrics recording failed", exc_info=True)
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_compaction(name, generation)
    except Exception:
        logger.debug("compaction counter update failed", exc_info=True)
