"""Distributed k-means over a device mesh: the semantic-dedup core.

Equivalent capability of the reference's multi-GPU dedup
(cosmos_curate/pipelines/video/dedup/dedup_actor.py:197-237 — cuML
``KMeansMG`` over NCCL bootstrapped by RAFT, raft_actor.py:84-131). The
TPU-native re-design has no NCCL and no actor pool: embeddings are sharded
over the mesh's data axes, centroids are replicated, and each Lloyd
iteration is ONE jitted program — XLA inserts the cross-device ``psum`` for
the centroid sums exactly where the reference ran NCCL all-reduce. The hot
op (points x centroids similarity) is a single large matmul on the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@functools.partial(jax.jit, static_argnames=("k", "valid"))
def _init_centroids(data: jax.Array, k: int, seed: int, valid: int) -> jax.Array:
    """Greedy k-means++-style seeding: first centroid sampled from the real
    rows, each next one the point FURTHEST (lowest max cosine similarity)
    from every centroid chosen so far. Uniform sampling of all k seeds made
    the result hinge on the PRNG's whims — two seeds landing in one true
    cluster is a bad local minimum Lloyd never escapes, and which seeds you
    get varies across jax versions/platforms (the tier-1 environment
    sensitivity this replaced). Rows beyond ``valid`` are mesh padding and
    masked out."""
    n = data.shape[0]
    mask = jnp.arange(n) < valid
    i0 = jax.random.choice(
        jax.random.PRNGKey(seed), n, p=mask / jnp.maximum(mask.sum(), 1)
    )
    cents = jnp.zeros((k, data.shape[1]), data.dtype).at[0].set(data[i0])
    best = data @ data[i0]  # max similarity to any chosen centroid

    def body(carry, j):
        cents, best = carry
        idx = jnp.argmin(jnp.where(mask, best, jnp.inf))
        c = data[idx]
        cents = cents.at[j].set(c)
        best = jnp.maximum(best, data @ c)
        return (cents, best), None

    (cents, _), _ = jax.lax.scan(body, (cents, best), jnp.arange(1, k))
    return cents


@jax.jit
def _lloyd_step(data, centroids, valid):
    """One Lloyd iteration. data: [N, D] (rows beyond ``valid`` are padding),
    centroids: [K, D]. Returns (new_centroids, assignments, shift)."""
    sims = data @ centroids.T  # [N, K] — the MXU matmul
    assign = jnp.argmax(sims, axis=1)
    mask = (jnp.arange(data.shape[0]) < valid)[:, None]
    one_hot = jax.nn.one_hot(assign, centroids.shape[0], dtype=data.dtype) * mask
    sums = one_hot.T @ data  # [K, D] — psum inserted here under sharding
    counts = one_hot.sum(axis=0)[:, None]
    new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1), centroids)
    norms = jnp.linalg.norm(new, axis=1, keepdims=True)
    new = new / jnp.maximum(norms, 1e-8)
    shift = jnp.linalg.norm(new - centroids, axis=1).max()
    return new, assign, shift


def kmeans_fit(
    embeddings: np.ndarray,
    k: int,
    *,
    iters: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    mesh=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Fit spherical k-means; returns (centroids [K, D], assignments [N]).

    With ``mesh``, rows shard over its data axes and every iteration's
    centroid reduction rides the mesh collectives; without, single device.
    Embeddings are L2-normalized (cosine geometry, like the reference's
    cosine pruning).
    """
    n, d = embeddings.shape
    k = min(k, n)
    data = embeddings / np.maximum(np.linalg.norm(embeddings, axis=1, keepdims=True), 1e-8)
    valid = n
    # Degrade cleanly instead of crashing the dedup run: a 1-device mesh
    # (the CPU tier-1 environment) adds nothing but sharding overhead, and a
    # mesh the batch cannot ride (device-put failure, dead backend) must
    # fall back to the single-device path — same numerics either way.
    if mesh is not None and getattr(mesh, "size", 1) <= 1:
        mesh = None
    if mesh is not None:
        from cosmos_curate_tpu.parallel.sharding import shard_batch

        try:
            data, _pad = shard_batch(mesh, data.astype(np.float32))
        except Exception as e:
            logger.warning("mesh sharding unavailable (%s); single-device kmeans", e)
            mesh = None
            data = jnp.asarray(data, jnp.float32)
    else:
        data = jnp.asarray(data, jnp.float32)

    centroids = _init_centroids(data, k, seed, valid)
    assign = None
    for i in range(iters):
        centroids, assign, shift = _lloyd_step(data, centroids, valid)
        if float(shift) < tol:
            logger.info("kmeans converged after %d iters (shift %.2e)", i + 1, float(shift))
            break
    return np.asarray(centroids), np.asarray(assign)[:n]


def semantic_dedup(
    embeddings: np.ndarray,
    ids: list[str],
    *,
    n_clusters: int | None = None,
    eps: float = 0.07,
    iters: int = 20,
    seed: int = 0,
    mesh=None,
) -> dict:
    """SemDeDup-style pruning (public technique; reference drives the same
    shape via cuML): cluster, then within each cluster drop items whose
    max cosine similarity to an already-kept item exceeds ``1 - eps``.

    Returns {"kept": [...], "removed": [...], "duplicate_of": {id: id},
    "assignments": np.ndarray}.
    """
    n = len(ids)
    if n == 0:
        return {"kept": [], "removed": [], "duplicate_of": {}, "assignments": np.zeros(0, int)}
    k = n_clusters or max(1, int(np.sqrt(n)))
    _, assign = kmeans_fit(embeddings, k, iters=iters, seed=seed, mesh=mesh)
    normed = embeddings / np.maximum(np.linalg.norm(embeddings, axis=1, keepdims=True), 1e-8)
    kept: list[str] = []
    removed: list[str] = []
    duplicate_of: dict[str, str] = {}
    threshold = 1.0 - eps
    for c in np.unique(assign):
        members = np.flatnonzero(assign == c)
        sims = normed[members] @ normed[members].T  # small per-cluster block
        kept_local: list[int] = []
        for j, m in enumerate(members):
            dup_idx = next(
                (kl for kl in kept_local if sims[j, kl] > threshold), None
            )
            if dup_idx is None:
                kept_local.append(j)
                kept.append(ids[m])
            else:
                removed.append(ids[m])
                duplicate_of[ids[m]] = ids[members[dup_idx]]
    return {
        "kept": kept,
        "removed": removed,
        "duplicate_of": duplicate_of,
        "assignments": assign,
    }
