"""Persistent storage layer for the sharded corpus embedding index.

Layout (mirroring the reference's lance fragment flow —
``write_lance_fragments`` staged per chunk, consolidated at end of run,
storage/lance_export.py docstring):

    <root>/meta.json                   index metadata (model, dim, k, counts)
    <root>/centroids.npy               [K, D] float32 L2-normalized centroids
    <root>/pending/<tag>.(parquet|lance)    in-pipeline fragment appends
    <root>/clusters/c<cid>/<frag>.(parquet|lance)   per-cluster vector shards
    <root>/manifests/gen-<NNNNNN>.json      immutable snapshot manifests
    <root>/MANIFEST.json               pointer: the current published generation
    <root>/centroids-<NNNNNN>.npy      per-generation centroids (compaction)

``ClipWriterStage`` appends *pending* fragments during a run (cheap,
append-only, no coordination); the end-of-run consolidation step routes
them into per-cluster shards against the trained centroids
(dedup/corpus_index.py). Fragments are **lance** datasets when ``pylance``
imports and the root is a local path, **parquet** otherwise (VERDICT #7 —
the lance wheel is absent from this image, so parquet is the tested
default and lance is driven through the same ``write_dataset`` /
``dataset`` call shape the export tool uses).

Vectors are stored L2-normalized (cosine geometry, matching
dedup/kmeans.py) with a ``provenance`` column per row — "random" rows
(embeddings from unstaged random-init weights, models/registry.py
``weights_provenance``) are refused at consolidation so they can never
poison the corpus.

**Manifests** make reads snapshot-isolated for the serving path
(dedup/index_server.py): a manifest pins the exact fragment set (and
centroids file) of one *generation*; readers open a generation and never
see fragments published after it. Publication is two writes — the
immutable ``manifests/gen-<N>.json`` first, then the tiny
``MANIFEST.json`` pointer (atomic rename on local roots) — so a reader
observes either the old or the new generation, never a half-published
one. Background compaction (dedup/compaction.py) is the only writer of
manifests; fragments referenced by a superseded manifest are deleted only
after every reader has dropped that generation.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from pathlib import Path

import numpy as np

from cosmos_curate_tpu.storage.client import (
    get_storage_client,
    is_remote_path,
    read_bytes,
    write_bytes,
)
from cosmos_curate_tpu.storage.writers import write_json, write_npy, write_parquet
from cosmos_curate_tpu.utils import schema_stamp
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ALLOW_RANDOM_ENV = "CURATE_INDEX_ALLOW_RANDOM"


def allow_random_provenance() -> bool:
    """Opt-in escape hatch: index vectors whose weights provenance is
    "random" anyway (integration tests, architecture-only runs). Production
    default is to refuse — a corpus index of noise silently dedups real
    clips against garbage."""
    return os.environ.get(ALLOW_RANDOM_ENV, "").lower() in ("1", "true", "on")


def _lance_module():
    try:
        import lance  # noqa: PLC0415

        return lance
    except ImportError:
        return None


def _decode_embedding_column(column, n: int) -> np.ndarray:
    """list<float> column -> [N, D] float32 via the arrow values buffer —
    per-row ``to_pylist`` conversion is ~100x slower and was the query
    path's shard-load bottleneck. Falls back to the slow path for chunk
    layouts without a flat values buffer."""
    if n == 0:
        return np.zeros((0, 0), np.float32)
    try:
        arr = column.combine_chunks() if hasattr(column, "combine_chunks") else column
        flat = np.asarray(arr.values, dtype=np.float32)
        return flat.reshape(n, -1)
    except (AttributeError, ValueError, TypeError):
        return np.asarray(
            [np.asarray(v, np.float32) for v in column.to_pylist()], np.float32
        ).reshape(n, -1)


def normalize_rows(vecs: np.ndarray) -> np.ndarray:
    vecs = np.asarray(vecs, np.float32)
    return vecs / np.maximum(np.linalg.norm(vecs, axis=1, keepdims=True), 1e-8)


class IndexStore:
    """Fragment-level IO for one index root; backend resolved once per
    instance (pinned by ``meta.json`` when the index exists, so readers and
    writers of one index always agree)."""

    def __init__(self, root: str, *, backend: str | None = None) -> None:
        self.root = str(root).rstrip("/")
        meta = self.load_meta()
        if backend is None:
            backend = meta.get("backend") if meta else None
        if backend is None:
            backend = (
                "lance"
                if _lance_module() is not None and not is_remote_path(self.root)
                else "parquet"
            )
        if backend not in ("lance", "parquet"):
            raise ValueError(f"unknown index backend {backend!r}")
        if backend == "lance" and (
            _lance_module() is None or is_remote_path(self.root)
        ):
            logger.warning(
                "lance backend unavailable for %s; falling back to parquet", self.root
            )
            backend = "parquet"
        self.backend = backend

    # -- paths ---------------------------------------------------------------

    @property
    def meta_path(self) -> str:
        return f"{self.root}/meta.json"

    @property
    def centroids_path(self) -> str:
        return f"{self.root}/centroids.npy"

    def _fragment_path(self, *parts: str) -> str:
        ext = "lance" if self.backend == "lance" else "parquet"
        return f"{self.root}/{'/'.join(parts)}.{ext}"

    @staticmethod
    def cluster_dir(cid: int) -> str:
        return f"c{cid:05d}"

    # -- meta / centroids ----------------------------------------------------

    def exists(self) -> bool:
        client = get_storage_client(self.root)
        return client.exists(self.meta_path) and client.exists(self.centroids_path)

    def load_meta(self) -> dict:
        client = get_storage_client(self.root)
        if not client.exists(f"{self.root}/meta.json"):
            return {}
        try:
            return json.loads(client.read_bytes(f"{self.root}/meta.json"))
        except (OSError, ValueError) as e:
            raise RuntimeError(f"unreadable index meta at {self.root}: {e}") from e

    def save_meta(self, meta: dict) -> None:
        write_json(self.meta_path, {**meta, "backend": self.backend})

    def load_centroids(self, rel: str | None = None) -> np.ndarray:
        """Centroids for ``rel`` (a manifest's pinned centroids file,
        relative to the root) or the live ``centroids.npy``."""
        path = f"{self.root}/{rel}" if rel else self.centroids_path
        return np.load(io.BytesIO(read_bytes(path)))

    def save_centroids(self, centroids: np.ndarray, *, generation: int | None = None) -> str:
        """Write centroids; a ``generation`` writes an immutable per-gen
        file (``centroids-<N>.npy``) so published manifests never see their
        centroids mutate underneath them. Returns the root-relative path."""
        rel = f"centroids-{generation:06d}.npy" if generation else "centroids.npy"
        write_npy(f"{self.root}/{rel}", np.asarray(centroids, np.float32))
        return rel

    # -- manifests (snapshot-isolated read generations) ----------------------

    @property
    def manifest_pointer_path(self) -> str:
        return f"{self.root}/MANIFEST.json"

    def manifest_path(self, generation: int) -> str:
        return f"{self.root}/manifests/gen-{generation:06d}.json"

    def current_generation(self) -> int:
        """The published generation, or 0 when no manifest exists yet
        (generation 0 = the live, unpinned view)."""
        client = get_storage_client(self.root)
        if not client.exists(self.manifest_pointer_path):
            return 0
        try:
            return int(json.loads(client.read_bytes(self.manifest_pointer_path))["generation"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise RuntimeError(f"unreadable manifest pointer at {self.root}: {e}") from e

    def read_manifest(self, generation: int | None = None) -> dict:
        """The manifest of ``generation`` (default: current). Generation 0
        (or no published manifest) synthesizes a live manifest from the
        current fragment listing — old indexes keep working unmanaged."""
        gen = self.current_generation() if generation is None else generation
        if gen <= 0:
            return self.build_live_manifest()
        client = get_storage_client(self.root)
        try:
            # manifests from a pre-stamp build (v1) migrate through the shim
            # chain; a manifest published by a NEWER build than this reader
            # raises SchemaVersionError — serving against a layout this
            # build cannot interpret is worse than failing the open
            return schema_stamp.upgrade(
                json.loads(client.read_bytes(self.manifest_path(gen))),
                "index-manifest",
            )
        except (OSError, ValueError) as e:
            raise RuntimeError(
                f"unreadable manifest gen {gen} at {self.root}: {e}"
            ) from e

    def build_live_manifest(self) -> dict:
        """Generation-0 view: the CURRENT fragment listing, shaped like a
        published manifest (per-cluster root-relative fragment paths +
        bytes, live centroids/meta). Not isolated — concurrent writers are
        visible — which is exactly why compaction publishes real ones."""
        clusters: dict[str, dict] = {}
        for cid in self.cluster_fragment_counts():
            frags = self.fragment_info(f"clusters/{self.cluster_dir(cid)}")
            clusters[str(cid)] = {
                "fragments": [rel for rel, _sz in frags],
                "bytes": int(sum(sz for _rel, sz in frags)),
                "rows": 0,  # unknown without reading; compaction fills it
            }
        return schema_stamp.stamp(
            {
                "generation": 0,
                "centroids": "centroids.npy",
                "meta": self.load_meta(),
                "clusters": clusters,
            },
            "index-manifest",
        )

    def publish_manifest(self, manifest: dict) -> int:
        """Write the immutable generation file, then flip the pointer. The
        pointer write is an atomic rename on local roots; on remote roots
        it is a single small PUT (last-writer-wins — compaction is the
        single manifest writer by contract)."""
        gen = int(manifest["generation"])
        if gen <= 0:
            raise ValueError("published generations start at 1")
        write_json(self.manifest_path(gen), schema_stamp.stamp(dict(manifest), "index-manifest"))
        # LocalStorageClient.write_bytes is tmp+rename (atomic on POSIX);
        # remote backends PUT one small object — either way a reader sees
        # the old pointer or the new one, never a torn file
        write_bytes(
            self.manifest_pointer_path,
            json.dumps(
                schema_stamp.stamp({"generation": gen}, "index-manifest")
            ).encode(),
        )
        return gen

    def list_manifests(self) -> list[int]:
        base = f"{self.root}/manifests"
        client = get_storage_client(base)
        gens = []
        for info in client.list_files(base, suffixes=(".json",)):
            name = info.path.rsplit("/", 1)[-1]
            if name.startswith("gen-") and name[4:-5].isdigit():
                gens.append(int(name[4:-5]))
        return sorted(gens)

    def delete_manifest(self, generation: int) -> None:
        client = get_storage_client(self.root)
        try:
            client.delete(self.manifest_path(generation))
        except OSError:
            logger.debug("manifest gen %d already gone", generation)

    # -- fragment IO ---------------------------------------------------------

    def _write_rows(
        self,
        path: str,
        ids: list[str],
        vecs: np.ndarray,
        *,
        model: str = "",
        provenance: str = "",
    ) -> None:
        columns = {
            "clip_uuid": [str(i) for i in ids],
            "embedding": [v.tolist() for v in np.asarray(vecs, np.float32)],
            "model": [model] * len(ids),
            "provenance": [provenance] * len(ids),
        }
        if self.backend == "lance":
            import pyarrow as pa

            # overwrite: fragment names are content-derived, so a re-run of
            # the same consolidation replaces its own fragment idempotently
            _lance_module().write_dataset(pa.table(columns), path, mode="overwrite")
        else:
            write_parquet(path, columns)

    def _read_rows(self, path: str) -> tuple[list[str], np.ndarray, list[str], list[str]]:
        if self.backend == "lance":
            table = _lance_module().dataset(path).to_table()
        else:
            import pyarrow.parquet as pq

            table = pq.read_table(io.BytesIO(read_bytes(path)))
        ids = [str(u) for u in table.column("clip_uuid").to_pylist()]
        vecs = _decode_embedding_column(table.column("embedding"), len(ids))
        names = table.column_names
        models = table.column("model").to_pylist() if "model" in names else [""] * len(ids)
        provs = (
            table.column("provenance").to_pylist()
            if "provenance" in names
            else [""] * len(ids)
        )
        return ids, vecs, models, provs

    def _list_fragments(self, subdir: str) -> list[str]:
        """Fragment paths under ``<root>/<subdir>`` for this backend. Lance
        datasets are directories, so they are found by probing the parent
        listing for ``.lance`` path components rather than file suffixes."""
        base = f"{self.root}/{subdir}"
        if self.backend == "lance":
            p = Path(base)
            if not p.is_dir():
                return []
            return sorted(str(d) for d in p.iterdir() if d.name.endswith(".lance"))
        client = get_storage_client(base)
        return sorted(
            info.path for info in client.list_files(base, suffixes=(".parquet",))
        )

    def _delete_fragment(self, path: str) -> None:
        get_storage_client(path).delete(path)

    def _relpath(self, path: str) -> str:
        """Root-relative form of a fragment path (manifests store relative
        paths so an index directory is relocatable)."""
        path = str(path)
        prefix = f"{self.root}/"
        return path[len(prefix):] if path.startswith(prefix) else path

    def fragment_info(self, subdir: str) -> list[tuple[str, int]]:
        """(root-relative path, size bytes) per fragment under ``subdir``.
        Lance datasets are directories; their size is the sum of their
        files (best-effort — sizing feeds cache budgets, not correctness)."""
        out: list[tuple[str, int]] = []
        for path in self._list_fragments(subdir):
            if self.backend == "lance":
                p = Path(path)
                size = sum(f.stat().st_size for f in p.rglob("*") if f.is_file()) if p.is_dir() else 0
            else:
                client = get_storage_client(path)
                size = 0
                for info in client.list_files(path, suffixes=(".parquet",)):
                    size += int(getattr(info, "size", 0) or 0)
            out.append((self._relpath(path), size))
        return out

    def read_fragments(self, rel_paths: list[str]) -> tuple[list[str], np.ndarray]:
        """Read a pinned fragment set (manifest entries, root-relative) as
        one (ids, [N, D]) pair — the snapshot-isolated read path. A
        fragment deleted after its manifest was superseded raises, which is
        why GC waits for readers to drop the generation."""
        ids: list[str] = []
        chunks: list[np.ndarray] = []
        for rel in rel_paths:
            i, v, _m, _p = self._read_rows(f"{self.root}/{rel}")
            ids.extend(i)
            chunks.append(v)
        vecs = np.concatenate(chunks) if chunks else np.zeros((0, 0), np.float32)
        return ids, vecs

    def delete_fragments(self, rel_paths: list[str]) -> int:
        """Delete superseded fragments (compaction GC). Missing files are
        fine — a crashed earlier GC may have removed some already."""
        n = 0
        for rel in rel_paths:
            try:
                self._delete_fragment(f"{self.root}/{rel}")
                n += 1
            except (OSError, FileNotFoundError):
                logger.debug("fragment already gone: %s", rel)
        return n

    # -- pending fragments (in-pipeline appends) -----------------------------

    def write_pending_fragment(
        self,
        tag: str,
        ids: list[str],
        vecs: np.ndarray,
        *,
        model: str = "",
        provenance: str = "",
    ) -> str:
        """One append-only fragment under ``pending/`` — the in-pipeline
        write path (``ClipWriterStage``). Tags are chunk-scoped, so
        concurrent writer workers touch disjoint files. Vectors are
        normalized at write so every reader shares cosine geometry."""
        path = self._fragment_path("pending", tag)
        self._write_rows(
            path, ids, normalize_rows(vecs), model=model, provenance=provenance
        )
        return path

    def list_pending(self) -> list[str]:
        return self._list_fragments("pending")

    def read_pending(self) -> tuple[list[str], np.ndarray, list[str], list[str]]:
        """All pending rows concatenated: (ids, vecs [N, D], models, provs)."""
        ids: list[str] = []
        chunks: list[np.ndarray] = []
        models: list[str] = []
        provs: list[str] = []
        for path in self.list_pending():
            i, v, m, p = self._read_rows(path)
            ids.extend(i)
            chunks.append(v)
            models.extend(m)
            provs.extend(p)
        vecs = np.concatenate(chunks) if chunks else np.zeros((0, 0), np.float32)
        return ids, vecs, models, provs

    def clear_pending(self) -> int:
        n = 0
        for path in self.list_pending():
            self._delete_fragment(path)
            n += 1
        return n

    # -- per-cluster shards --------------------------------------------------

    def append_cluster(self, cid: int, ids: list[str], vecs: np.ndarray) -> str:
        """Append one fragment to cluster ``cid``'s shard. Fragment names are
        content-derived, so re-running a consolidation over the same rows
        overwrites rather than duplicates."""
        tag = hashlib.sha256("|".join(map(str, ids)).encode()).hexdigest()[:16]
        path = self._fragment_path("clusters", self.cluster_dir(cid), tag)
        self._write_rows(path, ids, normalize_rows(vecs))
        return path

    def read_cluster(self, cid: int) -> tuple[list[str], np.ndarray]:
        ids: list[str] = []
        chunks: list[np.ndarray] = []
        for path in self._list_fragments(f"clusters/{self.cluster_dir(cid)}"):
            i, v, _m, _p = self._read_rows(path)
            ids.extend(i)
            chunks.append(v)
        vecs = np.concatenate(chunks) if chunks else np.zeros((0, 0), np.float32)
        if len(set(ids)) != len(ids):
            # the LIVE view can see a row twice between a compaction publish
            # and GC (the consolidated fragment AND its superseded source).
            # Dedup by id: duplicate rows would eat per-shard top-k slots in
            # the query path (manifest readers pin exact sets and never hit
            # this).
            seen: set[str] = set()
            keep = [i for i, u in enumerate(ids) if not (u in seen or seen.add(u))]
            ids = [ids[i] for i in keep]
            vecs = vecs[keep]
        return ids, vecs

    def cluster_fragment_counts(self) -> dict[int, int]:
        """cid -> fragment count for clusters that have any data."""
        base = f"{self.root}/clusters"
        out: dict[int, int] = {}
        if self.backend == "lance":
            root = Path(base)
            dirs = sorted(d.name for d in root.iterdir() if d.is_dir()) if root.is_dir() else []
            for name in dirs:
                if name.startswith("c") and name[1:].isdigit():
                    n = len(self._list_fragments(f"clusters/{name}"))
                    if n:
                        out[int(name[1:])] = n
            return out
        client = get_storage_client(base)
        for info in client.list_files(base, suffixes=(".parquet",)):
            rel = info.path[len(base) :].lstrip("/")
            head = rel.split("/", 1)[0]
            if head.startswith("c") and head[1:].isdigit():
                cid = int(head[1:])
                out[cid] = out.get(cid, 0) + 1
        return out
