"""Persistent sharded IVF corpus index: dedup queries instead of per-run
k-means.

The per-run dedup (dedup/kmeans.py + pipelines/video/dedup.py) re-clusters
every run against itself — O(N·K·iters) each time, the wrong asymptotics
once every new clip must dedup against **all previously curated
embeddings** (ROADMAP item 5). This module turns the existing pjit k-means
into an IVF trainer and makes similarity search a first-class, device-
parallel pipeline surface:

- **centroids** come from :func:`~cosmos_curate_tpu.dedup.kmeans.kmeans_fit`
  (replicated centroids, mesh-sharded points — the trainer is unchanged);
- **corpus vectors** live in per-cluster shards (dedup/index_store.py:
  lance fragments when pylance imports, parquet fallback), appended
  in-pipeline by ``ClipWriterStage`` and consolidated at end of run;
- **queries** are batched, routed to the top-``nprobe`` clusters by one
  centroid matmul, then scored as ONE MXU matmul per probed shard via
  :func:`query_matmul` — a ``shard_map`` over the mesh's batch axes
  (``parallel/axes.py``), queries sharded, the shard replicated, exactly
  the SNIPPETS [3] naive-batch-sharding shape. Query groups pad to pow2
  buckets so the compiled-shape universe stays bounded.

Query cost is O(probed shards) per batch instead of O(N·K·iters) per run;
``incremental_dedup`` reproduces ``semantic_dedup``'s greedy keep-first
semantics against the index (batch-internal duplicates included). Every
add/query records ``pipeline_index_*`` metrics through
``observability/stage_timer.record_index_ops``.
"""

from __future__ import annotations

import functools
import threading
import time

import jax
import numpy as np

from cosmos_curate_tpu.dedup.index_store import IndexStore, allow_random_provenance, normalize_rows
from cosmos_curate_tpu.models.batching import next_pow2
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_NPROBE = 8
DEFAULT_TOP_K = 8
# Loaded cluster shards cached per index instance (id list + matrix).
# Eviction is BYTE-budgeted: an entry-count cap treats a 4 GB skew cluster
# and a 2 MB one as equal citizens, so one fat cluster used to evict the
# whole probe union (or, worse, N fat clusters fit "under" the cap and
# blew host memory). The entry cap survives as a secondary bound for
# pathological many-tiny-shard layouts. The budget must comfortably exceed
# the typical probe UNION (≈ min(Q·nprobe, K) shards) or every query batch
# re-reads its shards from storage — cache thrash, not caching.
CLUSTER_CACHE_ENTRIES_ENV = "CURATE_INDEX_CACHE_SHARDS"
DEFAULT_CLUSTER_CACHE_ENTRIES = 512
CLUSTER_CACHE_BYTES_ENV = "CURATE_INDEX_CACHE_BYTES"
DEFAULT_CLUSTER_CACHE_BYTES = 256 << 20


def _cluster_cache_entries() -> int:
    import os

    return max(
        1, int(os.environ.get(CLUSTER_CACHE_ENTRIES_ENV, "") or DEFAULT_CLUSTER_CACHE_ENTRIES)
    )


def cluster_cache_bytes() -> int:
    import os

    return max(
        1, int(os.environ.get(CLUSTER_CACHE_BYTES_ENV, "") or DEFAULT_CLUSTER_CACHE_BYTES)
    )


def shard_nbytes(ids: list[str], mat: np.ndarray) -> int:
    """Host-memory estimate for one loaded shard: the matrix plus a rough
    per-id string overhead (python str + list slot)."""
    return int(mat.nbytes) + 64 * len(ids)


class ShardCache:
    """Byte-budgeted LRU over loaded cluster shards, keyed by
    ``(generation, cluster_id)`` — THE shard cache, shared by the batch
    path (:class:`CorpusIndex`, generation 0 = the live view) and the
    serving read path (dedup/index_server.py snapshots).

    Entry count is irrelevant to what a cache costs — a skewed corpus has
    4 GB clusters next to 2 MB ones — so admission and eviction are sized
    by :func:`shard_nbytes` (budget: ctor arg, else the
    ``CURATE_INDEX_CACHE_BYTES`` env read per access so tests/operators
    can retune live). ``pinned`` keys (the in-flight batch's probe union)
    are never evicted mid-batch; a shard larger than the whole budget is
    refused at admission. ``max_entries`` (int or callable) survives as a
    secondary bound for pathological many-tiny-shard layouts.
    ``drop_generation`` purges a superseded snapshot's shards the moment
    its refcount drains; ``invalidate`` drops one stale entry after an
    in-place append.

    Thread-safe; hit/miss/evicted byte totals flow to
    ``stage_timer.record_search`` under ``metrics_name``.
    """

    def __init__(
        self,
        budget_bytes: int | None = None,
        *,
        metrics_name: str = "index_server",
        max_entries=None,
    ) -> None:
        self._budget_fixed = int(budget_bytes) if budget_bytes else None
        self._max_entries = max_entries
        self.metrics_name = metrics_name
        self._lock = threading.Lock()
        # insertion-ordered: oldest first = LRU victim order
        self._entries: dict[tuple[int, int], tuple[list[str], np.ndarray, int]] = {}
        self.bytes = 0
        self.hit_bytes = 0
        self.miss_bytes = 0
        self.evicted_bytes = 0

    @property
    def budget(self) -> int:
        return self._budget_fixed or cluster_cache_bytes()

    def _entry_cap(self) -> int | None:
        cap = self._max_entries
        return cap() if callable(cap) else cap

    def get(
        self,
        generation: int,
        cid: int,
        loader,
        pinned: frozenset[tuple[int, int]] = frozenset(),
    ) -> tuple[list[str], np.ndarray]:
        key = (generation, cid)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries[key] = self._entries.pop(key)  # LRU touch
                self.hit_bytes += entry[2]
                _record_search_bytes(self.metrics_name, cache_hit_bytes=entry[2])
                return entry[0], entry[1]
        ids, mat = loader()
        nbytes = shard_nbytes(ids, mat)
        budget = self.budget
        cap = self._entry_cap()
        with self._lock:
            self.miss_bytes += nbytes
            _record_search_bytes(self.metrics_name, cache_miss_bytes=nbytes)
            if nbytes > budget:
                return ids, mat  # admission by bytes: never cache the uncacheable
            evicted = 0
            for victim in [k for k in self._entries if k not in pinned]:
                if self.bytes + nbytes <= budget and (
                    cap is None or len(self._entries) < cap
                ):
                    break
                _vids, _vmat, vbytes = self._entries.pop(victim)
                self.bytes -= vbytes
                evicted += vbytes
            if evicted:
                self.evicted_bytes += evicted
                _record_search_bytes(self.metrics_name, cache_evicted_bytes=evicted)
            if (
                self.bytes + nbytes <= budget
                and (cap is None or len(self._entries) < cap)
                and key not in self._entries
            ):
                self._entries[key] = (ids, mat, nbytes)
                self.bytes += nbytes
        return ids, mat

    def invalidate(self, generation: int, cid: int) -> None:
        """Drop one entry (its backing shard grew; reload on demand)."""
        with self._lock:
            entry = self._entries.pop((generation, cid), None)
            if entry is not None:
                self.bytes -= entry[2]

    def drop_generation(self, generation: int) -> int:
        """Purge every shard of a drained generation; returns bytes freed."""
        with self._lock:
            victims = [k for k in self._entries if k[0] == generation]
            freed = 0
            for key in victims:
                freed += self._entries.pop(key)[2]
            self.bytes -= freed
        if freed:
            logger.info(
                "shard cache: drained generation %d (%d shards, %.1f MB)",
                generation, len(victims), freed / 2**20,
            )
        return freed

    def stats(self) -> dict:
        with self._lock:
            return {
                "budget_bytes": self.budget,
                "resident_bytes": self.bytes,
                "resident_shards": len(self._entries),
                "hit_bytes": self.hit_bytes,
                "miss_bytes": self.miss_bytes,
                "evicted_bytes": self.evicted_bytes,
            }


def _record_search_bytes(name: str, **deltas) -> None:
    try:
        from cosmos_curate_tpu.observability.stage_timer import record_search

        record_search(name, **deltas)
    except Exception:  # metrics must never take down the read path
        logger.debug("search cache metrics recording failed", exc_info=True)


def query_matmul(mesh, queries, corpus, *, top_k: int):
    """Score a query batch against one corpus shard: ``[Q, D] @ [D, N]`` +
    per-row top-k, shard_map'd so the query batch shards over the mesh's
    batch axes while the corpus shard is replicated — the similarity search
    rides the MXU device-parallel like every other hot path. Accepts an
    ``AbstractMesh`` too, so shardcheck's ``ivf-query`` contract traces this
    exact call site device-free (analysis/shard_check.py)."""
    from jax.sharding import PartitionSpec as P

    from cosmos_curate_tpu.parallel.axes import BATCH_AXES
    from cosmos_curate_tpu.parallel.sharding import shard_map

    axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    qspec = P(axes) if axes else P(None)

    def _local(q, c):
        # unpack/re-pack: top_k's raw output is a list pytree in some jax
        # versions, which would mismatch the tuple out_specs
        vals, idxs = jax.lax.top_k(q @ c.T, top_k)
        return vals, idxs

    return shard_map(
        _local, mesh=mesh, in_specs=(qspec, P()), out_specs=(qspec, qspec)
    )(queries, corpus)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_single(q, c, k: int):
    """Single-device fallback of :func:`query_matmul` (no mesh attached)."""
    return jax.lax.top_k(q @ c.T, k)


class DeviceTopK:
    """One scoring matmul on the device plane: shard_map over the mesh's
    batch axes when a multi-device mesh is attached, plain jit otherwise.
    Holds the per-``top_k`` jit cache so the compiled-shape universe is
    shared across callers (CorpusIndex batch queries AND the index-server
    snapshot reader ride the same programs)."""

    def __init__(self, mesh=None) -> None:
        self.mesh = mesh if mesh is not None and getattr(mesh, "size", 1) > 1 else None
        self._mesh_jit: dict[int, object] = {}

    def __call__(self, q: np.ndarray, corpus: np.ndarray, k: int):
        """Host (vals, idxs) of the per-row top-k of ``q @ corpus.T``."""
        if self.mesh is not None:
            from cosmos_curate_tpu.parallel.sharding import shard_batch, unshard_batch

            fn = self._mesh_jit.get(k)
            if fn is None:
                fn = jax.jit(functools.partial(query_matmul, self.mesh, top_k=k))
                self._mesh_jit[k] = fn
            placed, pad = shard_batch(self.mesh, q)
            vals, idxs = fn(placed, corpus)
            return unshard_batch(jax.device_get((vals, idxs)), pad)
        return jax.device_get(_topk_single(q, corpus, k))


def route_queries(
    q: np.ndarray, centroids: np.ndarray, nprobe: int
) -> dict[int, list[int]]:
    """The routing matmul: cluster id -> query row indices that probe it
    (each query takes its top-``nprobe`` centroids). ``nprobe`` clamps to
    [1, K] — a negative value must not argpartition its way into probing
    the whole corpus."""
    cent_sims = q @ centroids.T  # [Q, K]
    nprobe = max(1, min(nprobe, centroids.shape[0]))
    probed = np.argpartition(cent_sims, -nprobe, axis=1)[:, -nprobe:]
    by_cluster: dict[int, list[int]] = {}
    for qi in range(len(q)):
        for cid in probed[qi]:
            by_cluster.setdefault(int(cid), []).append(qi)
    return by_cluster


def score_shards(
    q: np.ndarray,
    by_cluster: dict[int, list[int]],
    loaded: list[tuple[int, list[str], np.ndarray]],
    top_k: int,
    device_topk: DeviceTopK,
) -> list[list[tuple[str, float]]]:
    """One matmul per probed shard over the pow2-padded subset of queries
    that probed it; candidates merge on the host as arrays (per-element
    python dict folding was the query path's second bottleneck after shard
    loads). Shared by the batch path (CorpusIndex) and the snapshot reader
    (dedup/index_server.py)."""
    n = len(q)
    per_q_vals: list[list[np.ndarray]] = [[] for _ in range(n)]
    per_q_ids: list[list[np.ndarray]] = [[] for _ in range(n)]
    for cid, cids, mat in loaded:
        qidx = by_cluster[cid]
        sub = q[qidx]
        # pow2 pad: bounds the compiled-shape universe to {pow2 <= Q}
        # per shard size instead of one compile per ragged subset
        target = next_pow2(len(qidx))
        if target > len(qidx):
            sub = np.concatenate(
                [sub, np.zeros((target - len(qidx), sub.shape[1]), np.float32)]
            )
        kk = min(top_k, len(cids))
        vals, idxs = device_topk(sub, mat, kk)
        vals, idxs = vals[: len(qidx)], idxs[: len(qidx)]
        hit_ids = np.asarray(cids, object)[idxs]  # [m, kk] of id strings
        for row, qi in enumerate(qidx):
            per_q_vals[qi].append(vals[row])
            per_q_ids[qi].append(hit_ids[row])
    results: list[list[tuple[str, float]]] = []
    for qi in range(n):
        if not per_q_vals[qi]:
            results.append([])
            continue
        v = np.concatenate(per_q_vals[qi])
        ids_q = np.concatenate(per_q_ids[qi])
        row: list[tuple[str, float]] = []
        seen: set[str] = set()  # an id can surface from several shards
        for j in np.argsort(-v):
            hid = ids_q[j]
            if hid in seen:
                continue
            seen.add(hid)
            row.append((str(hid), float(v[j])))
            if len(row) == top_k:
                break
        results.append(row)
    return results


class CorpusIndex:
    """One opened index: centroids + meta in memory, cluster shards loaded
    (and cached) on demand. Construction is cheap; ``build`` / ``open`` are
    the entry points."""

    def __init__(
        self,
        store: IndexStore,
        meta: dict,
        centroids: np.ndarray,
        *,
        mesh=None,
        metrics_name: str = "corpus_index",
    ) -> None:
        self.store = store
        self.meta = meta
        self.centroids = np.asarray(centroids, np.float32)
        self._topk = DeviceTopK(mesh)
        self.mesh = self._topk.mesh
        self.metrics_name = metrics_name
        # the shared byte-budgeted LRU at generation 0 (the live view);
        # the legacy entry cap rides along as the secondary bound
        self.cache = ShardCache(
            metrics_name=metrics_name, max_entries=_cluster_cache_entries
        )

    # -- lifecycle -----------------------------------------------------------

    @staticmethod
    def exists(root: str) -> bool:
        return IndexStore(root).exists()

    @classmethod
    def open(cls, root: str, *, mesh=None, metrics_name: str = "corpus_index") -> "CorpusIndex":
        store = IndexStore(root)
        if not store.exists():
            raise FileNotFoundError(f"no corpus index at {root} (run `index build` first)")
        return cls(
            store, store.load_meta(), store.load_centroids(),
            mesh=mesh, metrics_name=metrics_name,
        )

    @classmethod
    def build(
        cls,
        root: str,
        ids: list[str],
        vecs: np.ndarray,
        *,
        model: str = "",
        k: int | None = None,
        iters: int = 20,
        seed: int = 0,
        mesh=None,
        provenance: str = "",
        backend: str | None = None,
        metrics_name: str = "corpus_index",
    ) -> "CorpusIndex":
        """Train centroids on ``vecs`` (reusing the pjit k-means) and write
        the initial per-cluster shards."""
        from cosmos_curate_tpu.dedup.kmeans import kmeans_fit

        if len(ids) == 0:
            raise ValueError("cannot build an index from zero vectors")
        t0 = time.monotonic()
        normed = normalize_rows(vecs)
        k = k or max(1, int(np.sqrt(len(ids))))
        centroids, assign = kmeans_fit(normed, k, iters=iters, seed=seed, mesh=mesh)
        store = IndexStore(root, backend=backend)
        store.save_centroids(centroids)
        for cid in np.unique(assign):
            members = np.flatnonzero(assign == cid)
            store.append_cluster(
                int(cid), [ids[m] for m in members], normed[members]
            )
        meta = {
            "version": 1,
            "model": model,
            "dim": int(normed.shape[1]),
            "k": int(centroids.shape[0]),
            "num_vectors": len(ids),
            "nprobe_default": DEFAULT_NPROBE,
            "provenance": provenance,
        }
        store.save_meta(meta)
        _record_index_ops(metrics_name, adds=len(ids), add_s=time.monotonic() - t0)
        logger.info(
            "built corpus index at %s: %d vectors, %d clusters, dim %d",
            root, len(ids), meta["k"], meta["dim"],
        )
        return cls(store, store.load_meta(), centroids, mesh=mesh, metrics_name=metrics_name)

    # -- writes --------------------------------------------------------------

    def add(self, ids: list[str], vecs: np.ndarray, *, normalized: bool = False) -> int:
        """Route ``vecs`` to their nearest centroids and append per-cluster
        fragments — O(N·K) routing + append IO, no re-clustering."""
        if len(ids) == 0:
            return 0
        t0 = time.monotonic()
        normed = np.asarray(vecs, np.float32) if normalized else normalize_rows(vecs)
        if normed.shape[1] != self.meta["dim"]:
            raise ValueError(
                f"vector dim {normed.shape[1]} != index dim {self.meta['dim']}"
            )
        assign = np.argmax(normed @ self.centroids.T, axis=1)
        for cid in np.unique(assign):
            members = np.flatnonzero(assign == cid)
            self.store.append_cluster(
                int(cid), [ids[m] for m in members], normed[members]
            )
            self.cache.invalidate(0, int(cid))  # shard grew; reload on demand
        self.meta["num_vectors"] = int(self.meta.get("num_vectors", 0)) + len(ids)
        self.store.save_meta(self.meta)
        _record_index_ops(self.metrics_name, adds=len(ids), add_s=time.monotonic() - t0)
        return len(ids)

    # -- queries -------------------------------------------------------------

    def _load_cluster(
        self, cid: int, pinned: frozenset[tuple[int, int]] = frozenset()
    ) -> tuple[list[str], np.ndarray]:
        """Load one cluster shard through the shared byte-budgeted LRU
        (generation 0 = the live view). ``pinned`` keys (the current
        batch's probe union) are never evicted — loading shard k of a wide
        probe pattern must not push out shard k-1 that the SAME batch just
        paid to load."""
        return self.cache.get(0, cid, lambda: self.store.read_cluster(cid), pinned)

    def query(
        self,
        vecs: np.ndarray,
        *,
        top_k: int = DEFAULT_TOP_K,
        nprobe: int | None = None,
        normalized: bool = False,
    ) -> list[list[tuple[str, float]]]:
        """Batched ANN search: per query, the ``top_k`` most-similar indexed
        vectors (id, cosine similarity), sorted descending, drawn from the
        union of every query's top-``nprobe`` centroid clusters. Each
        probed shard costs one device matmul over the pow2-padded subset
        of queries that probed it."""
        n = len(vecs)
        if n == 0:
            return []
        t0 = time.monotonic()
        q = np.asarray(vecs, np.float32) if normalized else normalize_rows(vecs)
        nprobe = nprobe or int(self.meta.get("nprobe_default", DEFAULT_NPROBE))
        by_cluster = route_queries(q, self.centroids, nprobe)
        # the probe union stays cached batch-long
        pinned = frozenset((0, cid) for cid in by_cluster)
        loaded = []
        for cid in sorted(by_cluster):
            cids, mat = self._load_cluster(cid, pinned)
            if cids:
                loaded.append((cid, cids, mat))
        # per-QUERY probe count (Σ over queries of non-empty probed shards,
        # ≈ n·nprobe), not the batch's deduplicated union — the metric's
        # ratio to queries must read as the effective nprobe
        probes = sum(len(by_cluster[cid]) for cid, _cids, _mat in loaded)
        if not loaded:
            results: list[list[tuple[str, float]]] = [[] for _ in range(n)]
        else:
            results = score_shards(q, by_cluster, loaded, top_k, self._topk)
        _record_index_ops(
            self.metrics_name,
            queries=n, probes=probes, query_s=time.monotonic() - t0,
        )
        return results

    def stats(self) -> dict:
        frags = self.store.cluster_fragment_counts()
        return {
            **self.meta,
            "index_path": self.store.root,
            "backend": self.store.backend,
            "clusters_with_data": len(frags),
            "fragments": int(sum(frags.values())),
            "pending_fragments": len(self.store.list_pending()),
        }


# -- dedup on top of the index ------------------------------------------------


def incremental_dedup(
    index: CorpusIndex,
    ids: list[str],
    vecs: np.ndarray,
    *,
    eps: float = 0.07,
    nprobe: int | None = None,
    top_k: int = DEFAULT_TOP_K,
) -> dict:
    """SemDeDup-style pruning of a NEW batch against the indexed corpus —
    the O(probed shards) replacement for re-running ``semantic_dedup`` over
    corpus+batch. Same greedy keep-first semantics: a batch item is a
    duplicate when an eligible neighbor sits within ``eps`` cosine distance;
    eligible means an indexed corpus item, or an EARLIER batch item that was
    itself kept (batch-internal duplicates are caught by an exact pass over
    the kept set, so the result matches ``semantic_dedup`` on well-separated
    data). Returns the ``semantic_dedup`` result shape."""
    n = len(ids)
    if n == 0:
        return {"kept": [], "removed": [], "duplicate_of": {}}
    normed = normalize_rows(vecs)
    hits = index.query(normed, top_k=top_k, nprobe=nprobe, normalized=True)
    threshold = 1.0 - eps
    pos = {cid: i for i, cid in enumerate(ids)}
    kept: list[str] = []
    removed: list[str] = []
    duplicate_of: dict[str, str] = {}
    removed_set: set[str] = set()
    kept_rows: list[int] = []
    for i, qid in enumerate(ids):
        dup = None
        for hid, sim in hits[i]:
            if sim <= threshold:
                break  # hits sorted descending — nothing closer follows
            if hid == qid or hid in removed_set:
                continue
            if pos.get(hid, -1) > i:
                # a LATER batch item (the index may already contain this
                # very batch, e.g. the in-pipeline writer ran first):
                # keep-first semantics say IT defers to US, not vice versa
                continue
            dup = hid
            break
        if dup is None and kept_rows:
            # exact batch-internal pass over the kept set: IVF top-k against
            # the corpus cannot see batch items that are not indexed yet
            sims = normed[kept_rows] @ normed[i]
            j = int(np.argmax(sims))
            if float(sims[j]) > threshold:
                dup = ids[kept_rows[j]]
        if dup is None:
            kept.append(qid)
            kept_rows.append(i)
        else:
            removed.append(qid)
            duplicate_of[qid] = dup
            removed_set.add(qid)
    _record_index_ops(index.metrics_name, duplicates=len(removed))
    return {"kept": kept, "removed": removed, "duplicate_of": duplicate_of}


def consolidate_index(
    root: str,
    *,
    k: int | None = None,
    iters: int = 20,
    mesh=None,
    metrics_name: str = "consolidate",
) -> dict:
    """End-of-run consolidation: fold the pending fragments ClipWriterStage
    appended during the run into per-cluster shards. Trains centroids via
    the pjit k-means when the index does not exist yet; routes against the
    existing centroids otherwise. Rows whose provenance is "random" are
    refused (counted in the result) unless ``CURATE_INDEX_ALLOW_RANDOM``
    opts in — noise embeddings must never become corpus memory."""
    store = IndexStore(root)
    ids, vecs, models, provs = store.read_pending()
    skipped = 0
    if ids and not allow_random_provenance():
        keep = [i for i, p in enumerate(provs) if p != "random"]
        skipped = len(ids) - len(keep)
        if skipped:
            logger.warning(
                "index consolidation: refusing %d random-provenance vectors "
                "(set %s=1 to override)", skipped, "CURATE_INDEX_ALLOW_RANDOM",
            )
        ids = [ids[i] for i in keep]
        models = [models[i] for i in keep]
        vecs = vecs[keep] if len(keep) else np.zeros((0, 0), np.float32)
    result = {"consolidated": 0, "skipped_random": skipped, "pending_cleared": 0}
    if not ids:
        result["pending_cleared"] = store.clear_pending() if skipped else 0
        return result
    # one embedding space per index: mixing models would compare
    # incompatible vectors (same rule as pipelines/video/dedup.py). An
    # existing index pins the model; otherwise the fragments elect it.
    model = store.load_meta().get("model") or next((m for m in models if m), "")
    if model:
        keep = [i for i, m in enumerate(models) if m in (model, "")]
        if len(keep) != len(ids):
            logger.warning(
                "index consolidation: dropping %d rows from other embedding "
                "models (index model: %s)", len(ids) - len(keep), model,
            )
        ids = [ids[i] for i in keep]
        vecs = vecs[keep]
    if store.exists():
        index = CorpusIndex.open(root, mesh=mesh, metrics_name=metrics_name)
        index.add(ids, vecs, normalized=True)
    else:
        CorpusIndex.build(
            root, ids, vecs, model=model, k=k, iters=iters, mesh=mesh,
            metrics_name=metrics_name,
        )
    result["consolidated"] = len(ids)
    result["pending_cleared"] = store.clear_pending()
    return result


def _record_index_ops(name: str, **deltas) -> None:
    try:
        from cosmos_curate_tpu.observability.stage_timer import record_index_ops

        record_index_ops(name, **deltas)
    except Exception:  # metrics must never take down an index operation
        logger.debug("index metrics recording failed", exc_info=True)
