"""Index-server read path: warm shard cache, snapshot-isolated queries,
micro-batched similarity search at interactive latency.

PR 10 made the corpus index the system's memory; this module opens it to
users as a serving-scale read path (ROADMAP item 2). Three pieces:

- :class:`ShardCache` — loaded cluster shards cached under a **byte**
  budget (``CURATE_INDEX_CACHE_BYTES``), admission and eviction sized by
  what a shard actually costs in host memory, keyed by
  ``(generation, cluster)`` so superseded generations drain cleanly.
- :class:`IndexSnapshot` — an immutable view of one published manifest
  generation (dedup/index_store.py): the fragment set, centroids and meta
  are pinned at open, so reads NEVER contend with ingest —
  ``ClipWriterStage`` keeps appending to ``pending/`` and background
  compaction (dedup/compaction.py) keeps publishing new generations while
  in-flight queries see one consistent world. Refcounted: the last
  release of a superseded snapshot drains its shards from the cache.
- :class:`IndexServer` — the serving loop: concurrent ``search()`` calls
  micro-batch across requests into ONE routing matmul + one
  ``query_matmul`` per probed shard (the same shard_map'd device path as
  batch dedup, SNIPPETS [3]'s batch-sharding shape), with an explicit
  warmup pass over the hottest (largest) clusters at boot and snapshot
  adoption between batches. Clip-to-clip queries take an embedding or an
  indexed clip UUID; text-to-clip embeds the query through the CLIP text
  tower (models/clip_text.py) — provenance-gated like everything else:
  random-init text weights are refused unless
  ``CURATE_INDEX_ALLOW_RANDOM=1``.

Latency SLOs ride ``stage_timer.record_search`` → the
``search_latency_seconds`` histogram plus cache hit/miss byte counters
(engine/metrics.py); the flight recorder snapshots p50/p99 into
``run_report.json: search``.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time

import numpy as np

from cosmos_curate_tpu.dedup.corpus_index import (
    DEFAULT_NPROBE,
    DEFAULT_TOP_K,
    DeviceTopK,
    ShardCache,
    route_queries,
    score_shards,
    shard_nbytes,
)
from cosmos_curate_tpu.dedup.index_store import (
    IndexStore,
    allow_random_provenance,
    normalize_rows,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

WARMUP_BYTES_ENV = "CURATE_INDEX_WARMUP_BYTES"


class ProvenanceError(RuntimeError):
    """A query path would run on random-init weights (refused: similarity
    against noise is not search). ``CURATE_INDEX_ALLOW_RANDOM=1`` opts in
    for architecture-only tests."""


def warmup_bytes_default(cache_budget: int) -> int:
    env = os.environ.get(WARMUP_BYTES_ENV, "")
    if env:
        return max(0, int(env))
    return cache_budget // 2


# ---------------------------------------------------------------------------
# snapshot-isolated reader


class IndexSnapshot:
    """One manifest generation, pinned: fragment set, centroids and meta
    never change for the snapshot's lifetime, no matter what compaction
    publishes meanwhile. Refcounted — the server retains it per batch and
    the owner ref drops on adoption of a newer generation; the LAST
    release fires ``on_drain`` (cache purge + optional fragment GC)."""

    def __init__(
        self,
        store: IndexStore,
        manifest: dict,
        *,
        topk: DeviceTopK,
        cache: ShardCache,
        metrics_name: str = "index_server",
    ) -> None:
        self.store = store
        self.manifest = manifest
        self.generation = int(manifest.get("generation", 0))
        self.meta = dict(manifest.get("meta") or store.load_meta())
        self.centroids = np.asarray(
            store.load_centroids(manifest.get("centroids") or None), np.float32
        )
        self.clusters: dict[int, dict] = {
            int(cid): info for cid, info in (manifest.get("clusters") or {}).items()
        }
        self._topk = topk
        self.cache = cache
        self.metrics_name = metrics_name
        self._lock = threading.Lock()
        self._refs = 1  # the owner's ref
        self.on_drain = None
        # clip-uuid -> cluster id, accumulated from every shard that loads;
        # resolve_uuid scans not-yet-seen clusters (largest first) on miss
        self._uuid_to_cid: dict[str, int] = {}
        self._unscanned: list[int] = sorted(
            self.clusters, key=lambda c: -int(self.clusters[c].get("bytes", 0))
        )

    # -- lifecycle -----------------------------------------------------------

    def retain(self) -> "IndexSnapshot":
        with self._lock:
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            drained = self._refs <= 0
        if drained:
            self.cache.drop_generation(self.generation)
            cb, self.on_drain = self.on_drain, None
            if cb is not None:
                try:
                    cb(self)
                except Exception:  # GC must never take down the read path
                    logger.exception("snapshot drain callback failed")

    # -- reads ---------------------------------------------------------------

    def _load_cluster(
        self, cid: int, pinned: frozenset[tuple[int, int]]
    ) -> tuple[list[str], np.ndarray]:
        info = self.clusters.get(cid)
        if info is None:
            return [], np.zeros((0, 0), np.float32)
        ids, mat = self.cache.get(
            self.generation,
            cid,
            lambda: self.store.read_fragments(list(info.get("fragments") or [])),
            pinned,
        )
        with self._lock:
            for u in ids:
                self._uuid_to_cid.setdefault(u, cid)
            if cid in self._unscanned:
                self._unscanned.remove(cid)  # even when empty: resolve_uuid must terminate
        return ids, mat

    def query(
        self,
        vecs: np.ndarray,
        *,
        top_k: int = DEFAULT_TOP_K,
        nprobe: int | None = None,
        normalized: bool = False,
    ) -> list[list[tuple[str, float]]]:
        """Batched ANN search against THIS generation only (same semantics
        as ``CorpusIndex.query``; same device path via ``score_shards``)."""
        n = len(vecs)
        if n == 0:
            return []
        q = np.asarray(vecs, np.float32) if normalized else normalize_rows(vecs)
        nprobe = nprobe or int(self.meta.get("nprobe_default", DEFAULT_NPROBE))
        by_cluster = route_queries(q, self.centroids, nprobe)
        pinned = frozenset((self.generation, cid) for cid in by_cluster)
        loaded = []
        for cid in sorted(by_cluster):
            cids, mat = self._load_cluster(cid, pinned)
            if cids:
                loaded.append((cid, cids, mat))
        if not loaded:
            return [[] for _ in range(n)]
        return score_shards(q, by_cluster, loaded, top_k, self._topk)

    def resolve_uuid(self, clip_uuid: str) -> np.ndarray | None:
        """The indexed embedding of ``clip_uuid``, or None. Hits the
        accumulated uuid map first; a miss scans not-yet-loaded clusters
        (largest first, through the cache — resolution doubles as warmup).
        Worst case O(corpus bytes) for an absent id; serving deployments
        keep the map hot via warmup + steady traffic."""
        pinned: frozenset[tuple[int, int]] = frozenset()
        while True:
            with self._lock:
                cid = self._uuid_to_cid.get(clip_uuid)
                nxt = self._unscanned[0] if self._unscanned else None
            if cid is not None:
                ids, mat = self._load_cluster(cid, pinned)
                try:
                    return mat[ids.index(clip_uuid)]
                except ValueError:
                    return None  # map raced a drop; treat as absent
            if nxt is None:
                return None
            self._load_cluster(nxt, pinned)

    def warm(self, budget_bytes: int) -> int:
        """Boot warmup: load the hottest clusters — largest first, the ones
        most likely probed AND most expensive to fault in at request time —
        until ``budget_bytes`` of shards are resident. Returns bytes warmed."""
        warmed = 0
        for cid in sorted(
            self.clusters, key=lambda c: -int(self.clusters[c].get("bytes", 0))
        ):
            if warmed >= budget_bytes:
                break
            ids, mat = self._load_cluster(cid, frozenset())
            warmed += shard_nbytes(ids, mat)
        return warmed

    def num_vectors(self) -> int:
        return int(self.meta.get("num_vectors", 0))


# ---------------------------------------------------------------------------
# the server


class _SearchRequest:
    __slots__ = ("mode", "payload", "top_k", "nprobe", "event", "results",
                 "generation", "error", "t0")

    def __init__(self, mode: str, payload, top_k: int, nprobe: int | None) -> None:
        self.mode = mode          # "clip" | "uuid" | "text"
        self.payload = payload    # [n, D] vecs | uuid str | text str
        self.top_k = top_k
        self.nprobe = nprobe
        self.event = threading.Event()
        self.results = None
        self.generation = -1
        self.error: BaseException | None = None
        self.t0 = time.monotonic()


class IndexServer:
    """The serving read path over one index root.

    Concurrent ``search()`` calls enqueue; a single worker thread drains
    the queue in micro-batches (``batch_window_s`` linger, ``max_batch``
    cap), resolves UUID/text payloads to embeddings, and answers every
    request in the batch from ONE retained snapshot — so a batch is
    generation-consistent by construction, and snapshot adoption (new
    compaction generations) happens strictly BETWEEN batches.
    """

    def __init__(
        self,
        root: str,
        *,
        mesh=None,
        cache_bytes: int | None = None,
        warmup: bool = True,
        warmup_budget: int | None = None,
        text_model: str = "clip-text-b-tpu",
        metrics_name: str = "index_server",
        batch_window_s: float = 0.002,
        max_batch: int = 64,
        adopt_interval_s: float = 1.0,
        gc_drained: bool = False,
    ) -> None:
        self.store = IndexStore(root)
        if not self.store.exists():
            raise FileNotFoundError(f"no corpus index at {root} (run `index build` first)")
        self.metrics_name = metrics_name
        self.text_model = text_model
        self.batch_window_s = batch_window_s
        self.max_batch = max_batch
        self.adopt_interval_s = adopt_interval_s
        self.gc_drained = gc_drained
        self._topk = DeviceTopK(mesh)
        self.cache = ShardCache(cache_bytes, metrics_name=metrics_name)
        self._snapshot = self._open_snapshot(self.store.current_generation())
        self._snap_lock = threading.Lock()
        self._last_adopt_check = time.monotonic()
        self._text_tower = None
        self._text_lock = threading.Lock()
        self._queue: queue_mod.Queue[_SearchRequest | None] = queue_mod.Queue()
        # guards the closed-check + enqueue pair: once close() sets the
        # flag (under this lock) and enqueues the sentinel, no request can
        # land BEHIND the sentinel, so the worker's drain-on-exit plus the
        # flag check covers every submitter — no request is left waiting
        # on an event nobody will set
        self._submit_lock = threading.Lock()
        self._closed = False
        self.warmed_bytes = 0
        if warmup:
            budget = (
                warmup_budget
                if warmup_budget is not None
                else warmup_bytes_default(self.cache.budget)
            )
            t0 = time.monotonic()
            self.warmed_bytes = self._snapshot.warm(budget)
            logger.info(
                "index server warmup: %.1f MB of shards resident in %.2fs "
                "(generation %d, %d vectors)",
                self.warmed_bytes / 2**20, time.monotonic() - t0,
                self._snapshot.generation, self._snapshot.num_vectors(),
            )
        _set_generation(self.metrics_name, self._snapshot.generation)
        self._worker = threading.Thread(
            target=self._serve_loop, name="index-server", daemon=True
        )
        self._worker.start()

    # -- snapshot lifecycle --------------------------------------------------

    def _open_snapshot(self, generation: int) -> IndexSnapshot:
        return IndexSnapshot(
            self.store,
            self.store.read_manifest(generation),
            topk=self._topk,
            cache=self.cache,
            metrics_name=self.metrics_name,
        )

    @property
    def generation(self) -> int:
        return self._snapshot.generation

    def maybe_adopt(self) -> bool:
        """Adopt the latest published generation (between batches). The old
        snapshot's owner ref drops; its shards drain from the cache when
        the last in-flight reader releases it."""
        try:
            latest = self.store.current_generation()
        except RuntimeError as e:
            logger.warning("manifest pointer unreadable; keeping generation %d (%s)",
                           self._snapshot.generation, e)
            return False
        if latest <= self._snapshot.generation:
            return False
        new = self._open_snapshot(latest)
        with self._snap_lock:
            old, self._snapshot = self._snapshot, new
        if self.gc_drained:
            old.on_drain = self._gc_snapshot
        old.release()  # owner ref; in-flight batches still hold theirs
        _set_generation(self.metrics_name, latest)
        _record_search(self.metrics_name, generations_adopted=1)
        logger.info(
            "index server adopted generation %d (was %d)", latest, old.generation
        )
        return True

    def _gc_snapshot(self, old: IndexSnapshot) -> None:
        """Drain-time GC: delete fragments only the superseded manifest
        referenced (no newer manifest pins them)."""
        from cosmos_curate_tpu.dedup.compaction import gc_superseded

        gc_superseded(self.store, old.manifest, self._snapshot.manifest)

    def _current_snapshot(self) -> IndexSnapshot:
        with self._snap_lock:
            return self._snapshot.retain()

    # -- public API ----------------------------------------------------------

    def search(
        self,
        vecs: np.ndarray | None = None,
        *,
        clip_uuid: str | None = None,
        text: str | None = None,
        top_k: int = DEFAULT_TOP_K,
        nprobe: int | None = None,
    ) -> tuple[list[list[tuple[str, float]]], int]:
        """Blocking search; exactly one of ``vecs`` ([n, D] or [D]),
        ``clip_uuid``, ``text``. Returns (per-query hit lists, the
        generation that answered). Thread-safe — concurrent callers
        micro-batch into shared device matmuls."""
        given = [x is not None for x in (vecs, clip_uuid, text)]
        if sum(given) != 1:
            raise ValueError("exactly one of vecs/clip_uuid/text")
        if vecs is not None:
            q = np.asarray(vecs, np.float32)
            if q.ndim == 1:
                q = q[None]
            if q.ndim != 2 or q.shape[1] != int(self._snapshot.meta.get("dim", q.shape[1])):
                raise ValueError(
                    f"query dim {q.shape[-1]} != index dim {self._snapshot.meta.get('dim')}"
                )
            req = _SearchRequest("clip", normalize_rows(q), top_k, nprobe)
        elif clip_uuid is not None:
            req = _SearchRequest("uuid", str(clip_uuid), top_k, nprobe)
        else:
            req = _SearchRequest("text", str(text), top_k, nprobe)
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("index server is closed")
            self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        latency = time.monotonic() - req.t0
        # search_s is recorded per BATCH by the serving loop (its busy
        # wall), NOT per request — summing per-request latencies would make
        # the derived qps read as 1/mean-latency and underreport
        # micro-batched throughput by the concurrency factor
        _record_search(
            self.metrics_name,
            latency_s=latency,
            mode=req.mode,
            searches=1,
            queries=len(req.results),
        )
        return req.results, req.generation

    def stats(self) -> dict:
        snap = self._snapshot
        return {
            "generation": snap.generation,
            "num_vectors": snap.num_vectors(),
            "clusters": len(snap.clusters),
            "warmed_bytes": self.warmed_bytes,
            "cache": self.cache.stats(),
            "text_model": self.text_model,
        }

    def close(self) -> None:
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # the sentinel is the LAST queue entry
        self._worker.join(timeout=10.0)
        self._fail_pending()  # worker died/hung: nobody may wait forever
        with self._snap_lock:
            self._snapshot.release()

    def _fail_pending(self) -> None:
        """Fail every queued request (shutdown drain)."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue_mod.Empty:
                return
            if req is None:
                continue
            req.error = RuntimeError("index server is closed")
            req.event.set()

    # -- text tower ----------------------------------------------------------

    def _text_embeddings(self, texts: list[str]) -> np.ndarray:
        with self._text_lock:
            tower = self._text_tower
            if tower is None:
                from cosmos_curate_tpu.models.clip_text import CLIPTextEmbeddings
                from cosmos_curate_tpu.models.registry import weights_provenance

                if (
                    weights_provenance(self.text_model) == "random"
                    and not allow_random_provenance()
                ):
                    raise ProvenanceError(
                        f"text tower {self.text_model!r} has no staged weights — "
                        "text-to-clip search on random projections is refused "
                        "(set CURATE_INDEX_ALLOW_RANDOM=1 for architecture-only runs)"
                    )
                tower = CLIPTextEmbeddings(self.text_model)
                tower.setup()
                dim = int(self._snapshot.meta.get("dim", tower.embedding_dim))
                if tower.embedding_dim != dim:
                    raise ValueError(
                        f"text tower dim {tower.embedding_dim} != index dim {dim} "
                        "(text-to-clip needs the paired tower of the index's "
                        "embedding space)"
                    )
                self._text_tower = tower
            return tower.encode_texts(texts)

    # -- the serving loop ----------------------------------------------------

    def _serve_loop(self) -> None:
        while True:
            try:
                req = self._queue.get()
            except (EOFError, OSError):
                self._fail_pending()
                return
            if req is None:
                self._fail_pending()
                return
            batch = [req]
            deadline = time.monotonic() + self.batch_window_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue_mod.Empty:
                    break
                if nxt is None:
                    self._queue.put(None)  # re-arm shutdown for after this batch
                    break
                batch.append(nxt)
            # adoption strictly BETWEEN batches: every request in `batch`
            # is answered by one generation
            if time.monotonic() - self._last_adopt_check >= self.adopt_interval_s:
                self._last_adopt_check = time.monotonic()
                try:
                    self.maybe_adopt()
                except Exception:
                    logger.exception("snapshot adoption failed; serving old generation")
            snap = self._current_snapshot()
            t0 = time.monotonic()
            try:
                self._serve_batch(snap, batch)
            finally:
                snap.release()
            _record_search(
                self.metrics_name,
                batches=1,
                batched_requests=len(batch),
                search_s=time.monotonic() - t0,
            )

    def _serve_batch(self, snap: IndexSnapshot, batch: list[_SearchRequest]) -> None:
        # resolve uuid/text payloads to embeddings against THIS snapshot
        rows: list[np.ndarray] = []
        spans: list[tuple[_SearchRequest, int, int]] = []
        texts = [r for r in batch if r.mode == "text"]
        text_vecs: dict[int, np.ndarray] = {}
        if texts:
            try:
                embedded = self._text_embeddings([r.payload for r in texts])
                for i, r in enumerate(texts):
                    text_vecs[id(r)] = embedded[i][None]
            except BaseException as e:  # noqa: BLE001 — fail the text requests only
                for r in texts:
                    r.error, r.generation = e, snap.generation
                    r.event.set()
                batch = [r for r in batch if r.mode != "text"]
        for req in batch:
            if req.error is not None:
                continue
            try:
                if req.mode == "clip":
                    q = req.payload
                elif req.mode == "uuid":
                    vec = snap.resolve_uuid(req.payload)
                    if vec is None:
                        raise KeyError(f"clip_uuid {req.payload!r} is not indexed")
                    q = vec[None]
                else:
                    q = normalize_rows(text_vecs[id(req)])
            except BaseException as e:  # noqa: BLE001
                req.error, req.generation = e, snap.generation
                req.event.set()
                continue
            spans.append((req, len(rows), len(rows) + len(q)))
            rows.extend(q)
        if not spans:
            return
        # group by (top_k, nprobe): one snapshot.query per distinct knob set
        groups: dict[tuple[int, int | None], list[tuple[_SearchRequest, int, int]]] = {}
        for item in spans:
            groups.setdefault((item[0].top_k, item[0].nprobe), []).append(item)
        all_rows = np.asarray(rows, np.float32)
        for (top_k, nprobe), items in groups.items():
            idx = np.concatenate([np.arange(a, b) for _r, a, b in items])
            try:
                results = snap.query(
                    all_rows[idx], top_k=top_k, nprobe=nprobe, normalized=True
                )
            except BaseException as e:  # noqa: BLE001
                for r, _a, _b in items:
                    r.error, r.generation = e, snap.generation
                    r.event.set()
                continue
            pos = 0
            for r, a, b in items:
                n = b - a
                r.results = results[pos:pos + n]
                r.generation = snap.generation
                pos += n
                r.event.set()


# ---------------------------------------------------------------------------
# metrics plumbing (must never take down the read path)


def _record_search(name: str, *, latency_s: float | None = None, mode: str = "clip", **deltas) -> None:
    try:
        from cosmos_curate_tpu.observability.stage_timer import record_search

        record_search(name, latency_s=latency_s, mode=mode, **deltas)
    except Exception:
        logger.debug("search metrics recording failed", exc_info=True)


def _set_generation(name: str, generation: int) -> None:
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().set_index_generation(name, generation)
    except Exception:
        logger.debug("generation gauge update failed", exc_info=True)
