// Native runtime support for the streaming engine's object store.
//
// The reference rides Ray's C++ core + plasma store for zero-copy object
// transport (SURVEY.md §1 L0); our engine's equivalent hot path — framing
// task payloads into POSIX shared-memory segments — is implemented here so
// the per-object work is one open/ftruncate/mmap and one gather pass over
// the PEP-574 buffers, with no Python-level slice bookkeeping.
//
// Layout written (must match engine/object_store.py):
//   [u64 payload_len][payload][u64 nbuf][u64 size]*nbuf [buffers...]
//
// Exposed C ABI (ctypes):
//   cn_put(name, payload, payload_len, bufs, sizes, nbuf, total) -> 0/-errno

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapping {
    void* addr = nullptr;
    size_t size = 0;
    int fd = -1;
    bool ok() const { return addr != MAP_FAILED && addr != nullptr; }
};

Mapping map_segment(const char* name, size_t size, bool create) {
    Mapping m;
    int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
    m.fd = shm_open(name, flags, 0600);
    if (m.fd < 0) return m;
    if (create && ftruncate(m.fd, static_cast<off_t>(size)) != 0) {
        // failure after create must not orphan a half-made segment: the
        // Python fallback will re-create under the SAME name.
        close(m.fd);
        shm_unlink(name);
        m.fd = -1;
        return m;
    }
    m.size = size;
    m.addr = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, m.fd, 0);
    if (!m.ok()) {
        close(m.fd);
        if (create) shm_unlink(name);
        m.fd = -1;
    }
    return m;
}

void unmap(Mapping& m) {
    if (m.ok()) munmap(m.addr, m.size);
    if (m.fd >= 0) close(m.fd);
}

inline void put_u64(uint8_t*& p, uint64_t v) {
    std::memcpy(p, &v, 8);  // little-endian hosts only (TPU VMs are x86/ARM LE)
    p += 8;
}

}  // namespace

extern "C" {

int cn_put(const char* name, const uint8_t* payload, uint64_t payload_len,
           const uint8_t** bufs, const uint64_t* sizes, uint64_t nbuf,
           uint64_t total) {
    Mapping m = map_segment(name, total, /*create=*/true);
    if (!m.ok()) return -errno;
    uint8_t* p = static_cast<uint8_t*>(m.addr);
    put_u64(p, payload_len);
    std::memcpy(p, payload, payload_len);
    p += payload_len;
    put_u64(p, nbuf);
    for (uint64_t i = 0; i < nbuf; ++i) put_u64(p, sizes[i]);
    for (uint64_t i = 0; i < nbuf; ++i) {
        std::memcpy(p, bufs[i], sizes[i]);
        p += sizes[i];
    }
    unmap(m);
    return 0;
}

}  // extern "C"
