"""ctypes bindings for the native runtime library (curate_native.cpp).

Compiled on demand with g++ (cached beside the source; rebuilt when the
source changes). Absent a toolchain, callers fall back to the pure-Python
paths — the native library is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_SRC = Path(__file__).parent / "curate_native.cpp"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _build_dir() -> Path:
    # Per-user, mode-0700 directory: a predictable world-writable path would
    # let another local user plant a .so that we dlopen.
    default = f"/tmp/curate_native-{os.getuid()}"
    d = Path(os.environ.get("CURATE_NATIVE_BUILD_DIR", default))
    d.mkdir(parents=True, exist_ok=True, mode=0o700)
    st = d.stat()
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        raise RuntimeError(
            f"native build dir {d} is not exclusively owned by this user "
            f"(uid {st.st_uid}, mode {oct(st.st_mode)}); refusing to load"
        )
    return d


def load_native() -> ctypes.CDLL | None:
    """Compile (if needed) and load the native library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            src = _SRC.read_bytes()
            tag = hashlib.sha256(src).hexdigest()[:16]
            so = _build_dir() / f"libcurate_native-{tag}.so"
            if not so.exists():
                # build to a process-unique temp then atomically rename, so
                # concurrent workers can't observe a half-written .so
                tmp = so.with_name(f"{so.stem}.{os.getpid()}.tmp.so")
                cmd = [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    "-o", str(tmp), str(_SRC), "-lrt",
                ]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                tmp.replace(so)
                logger.info("built native library %s", so.name)
            lib = ctypes.CDLL(str(so))
            lib.cn_put.restype = ctypes.c_int
            lib.cn_put.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.c_uint64,
            ]
            _lib = lib
        except Exception as e:
            logger.warning("native library unavailable (%s); using Python path", e)
            _load_failed = True
    return _lib


_H264_SRC = Path(__file__).parent / "h264_encoder.c"
_h264_lock = threading.Lock()
_h264_lib: ctypes.CDLL | None = None
_h264_failed = False


def load_h264() -> ctypes.CDLL | None:
    """Compile (if needed) and load the H264 encoder binding; None when the
    toolchain or the ffmpeg dev libraries are absent (callers fall back to
    cv2's negotiated codec)."""
    global _h264_lib, _h264_failed
    if _h264_lib is not None or _h264_failed:
        return _h264_lib
    with _h264_lock:
        if _h264_lib is not None or _h264_failed:
            return _h264_lib
        try:
            src = _H264_SRC.read_bytes()
            tag = hashlib.sha256(src).hexdigest()[:16]
            so = _build_dir() / f"libcurate_h264-{tag}.so"
            if not so.exists():
                tmp = so.with_name(f"{so.stem}.{os.getpid()}.tmp.so")
                cmd = [
                    "gcc", "-O2", "-shared", "-fPIC",
                    "-o", str(tmp), str(_H264_SRC),
                    "-lavformat", "-lavcodec", "-lswscale", "-lavutil",
                ]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                tmp.replace(so)
                logger.info("built H264 encoder library %s", so.name)
            lib = ctypes.CDLL(str(so))
            lib.curate_h264_open.restype = ctypes.c_void_p
            lib.curate_h264_open.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_double,
                ctypes.c_int,
                ctypes.c_char_p,
            ]
            lib.curate_h264_write.restype = ctypes.c_int
            lib.curate_h264_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.curate_h264_close.restype = ctypes.c_int
            lib.curate_h264_close.argtypes = [ctypes.c_void_p]
            _h264_lib = lib
        except Exception as e:
            logger.warning("H264 encoder unavailable (%s); falling back to cv2", e)
            _h264_failed = True
    return _h264_lib
