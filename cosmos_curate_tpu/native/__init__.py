"""ctypes bindings for the native runtime libraries.

Compiled on demand (cached beside the source hash; rebuilt when a source
changes). Absent a toolchain or the needed system libraries, callers fall
back to the pure-Python paths — native code is an accelerator, never a
requirement.

Bindings:
- curate_native.cpp — shared-memory object-store framing (cn_put).
- h264_encoder.c — libx264 clip encoder over libavformat/libavcodec.
- mv_extract.c — codec motion-vector extraction (libavcodec export_mvs).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Callable

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _build_dir() -> Path:
    # Per-user, mode-0700 directory: a predictable world-writable path would
    # let another local user plant a .so that we dlopen.
    default = f"/tmp/curate_native-{os.getuid()}"
    d = Path(os.environ.get("CURATE_NATIVE_BUILD_DIR", default))
    d.mkdir(parents=True, exist_ok=True, mode=0o700)
    st = d.stat()
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        raise RuntimeError(
            f"native build dir {d} is not exclusively owned by this user "
            f"(uid {st.st_uid}, mode {oct(st.st_mode)}); refusing to load"
        )
    return d


class _Binding:
    """One compile-once-and-load native library: shared lock / source-hash
    cache / atomic-rename / prototype-configuration mechanics."""

    def __init__(
        self,
        src_name: str,
        *,
        stem: str,
        compiler: list[str],
        libs: list[str],
        configure: Callable[[ctypes.CDLL], None],
        fallback_note: str,
    ) -> None:
        self.src = Path(__file__).parent / src_name
        self.stem = stem
        self.compiler = compiler
        self.libs = libs
        self.configure = configure
        self.fallback_note = fallback_note
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._failed = False

    def load(self) -> ctypes.CDLL | None:
        if self._lib is not None or self._failed:
            return self._lib
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            try:
                tag = hashlib.sha256(self.src.read_bytes()).hexdigest()[:16]
                so = _build_dir() / f"{self.stem}-{tag}.so"
                if not so.exists():
                    # build to a process-unique temp then atomically rename,
                    # so concurrent workers can't observe a half-written .so
                    tmp = so.with_name(f"{so.stem}.{os.getpid()}.tmp.so")
                    cmd = [
                        *self.compiler, "-O2", "-shared", "-fPIC",
                        "-o", str(tmp), str(self.src), *self.libs,
                    ]
                    try:
                        # blocking under _lock is the POINT of this
                        # build-once lock: every concurrent load() must
                        # wait for the single compile, not race a second
                        # one (120 s cap bounds the stall)
                        # curate-lint: disable=lock-blocking
                        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                        tmp.replace(so)
                    finally:
                        tmp.unlink(missing_ok=True)  # failed builds must not litter
                    logger.info("built native library %s", so.name)
                lib = ctypes.CDLL(str(so))
                self.configure(lib)
                self._lib = lib
            except Exception as e:
                logger.warning("%s unavailable (%s); %s", self.stem, e, self.fallback_note)
                self._failed = True
        return self._lib


def _configure_native(lib: ctypes.CDLL) -> None:
    lib.cn_put.restype = ctypes.c_int
    lib.cn_put.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
        ctypes.c_uint64,
    ]


def _configure_h264(lib: ctypes.CDLL) -> None:
    lib.curate_h264_open.restype = ctypes.c_void_p
    lib.curate_h264_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_double,
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.curate_h264_write.restype = ctypes.c_int
    lib.curate_h264_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.curate_h264_close.restype = ctypes.c_int
    lib.curate_h264_close.argtypes = [ctypes.c_void_p]


def _configure_mv(lib: ctypes.CDLL) -> None:
    lib.curate_mv_field.restype = ctypes.c_int
    lib.curate_mv_field.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]


_AV_LIBS = ["-lavformat", "-lavcodec", "-lavutil"]

_NATIVE = _Binding(
    "curate_native.cpp",
    stem="libcurate_native",
    compiler=["g++", "-std=c++17"],
    libs=["-lrt"],
    configure=_configure_native,
    fallback_note="using Python path",
)
_H264 = _Binding(
    "h264_encoder.c",
    stem="libcurate_h264",
    compiler=["gcc"],
    libs=[*_AV_LIBS, "-lswscale"],
    configure=_configure_h264,
    fallback_note="falling back to cv2",
)
_MV = _Binding(
    "mv_extract.c",
    stem="libcurate_mv",
    compiler=["gcc"],
    libs=[*_AV_LIBS, "-lm"],
    configure=_configure_mv,
    fallback_note="frame-diff fallback",
)


def load_native() -> ctypes.CDLL | None:
    """Object-store framing accelerator; None -> Python path."""
    return _NATIVE.load()


def load_h264() -> ctypes.CDLL | None:
    """libx264 encoder binding; None -> cv2's negotiated codec."""
    return _H264.load()


def load_mv() -> ctypes.CDLL | None:
    """Motion-vector extraction binding; None -> frame-diff estimator."""
    return _MV.load()
