/* H264 clip encoder over libavformat/libavcodec (libx264).
 *
 * Capability match: the reference transcodes every curated clip to H264
 * (cosmos_curate/pipelines/video/clipping/clip_extraction_stages.py:167,
 * libopenh264 / h264_nvenc). The cv2 build in this image has no H264
 * encoder, so this binding goes straight to the system ffmpeg libraries;
 * cosmos_curate_tpu/video/encode.py negotiates it first and falls back to
 * cv2/mp4v when the library cannot be built or opened.
 *
 * API (C linkage, loaded via ctypes from cosmos_curate_tpu/native):
 *   curate_h264_open(path, w, h, fps, crf, preset) -> ctx or NULL
 *   curate_h264_write(ctx, bgr)  one [h, w, 3] BGR24 frame; 0 on success
 *   curate_h264_close(ctx)       flush + trailer + free; 0 on success
 */

#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/opt.h>
#include <libswscale/swscale.h>
#include <stdint.h>
#include <stdlib.h>

typedef struct {
    AVFormatContext *fmt;
    AVCodecContext *enc;
    AVStream *stream;
    struct SwsContext *sws;
    AVFrame *frame;
    AVPacket *pkt;
    int width, height;
    int64_t next_pts;
    int header_written;
} H264Ctx;

static void ctx_free(H264Ctx *c) {
    if (!c) return;
    if (c->sws) sws_freeContext(c->sws);
    if (c->frame) av_frame_free(&c->frame);
    if (c->pkt) av_packet_free(&c->pkt);
    if (c->enc) avcodec_free_context(&c->enc);
    if (c->fmt) {
        if (c->fmt->pb) avio_closep(&c->fmt->pb);
        avformat_free_context(c->fmt);
    }
    free(c);
}

void *curate_h264_open(const char *path, int w, int h, double fps, int crf,
                       const char *preset) {
    if (w <= 0 || h <= 0 || fps <= 0) return NULL;
    av_log_set_level(AV_LOG_ERROR); /* x264 banner noise off the worker logs */
    H264Ctx *c = calloc(1, sizeof(H264Ctx));
    if (!c) return NULL;
    c->width = w;
    c->height = h;

    if (avformat_alloc_output_context2(&c->fmt, NULL, "mp4", path) < 0) goto fail;
    const AVCodec *codec = avcodec_find_encoder_by_name("libx264");
    if (!codec) codec = avcodec_find_encoder(AV_CODEC_ID_H264);
    if (!codec) goto fail;

    c->stream = avformat_new_stream(c->fmt, NULL);
    c->enc = avcodec_alloc_context3(codec);
    if (!c->stream || !c->enc) goto fail;

    c->enc->width = w;
    c->enc->height = h;
    c->enc->pix_fmt = AV_PIX_FMT_YUV420P;
    /* millisecond-scaled time base handles fractional rates (29.97 etc.) */
    c->enc->time_base = (AVRational){1000, (int)(fps * 1000.0 + 0.5)};
    c->enc->framerate = (AVRational){(int)(fps * 1000.0 + 0.5), 1000};
    if (c->fmt->oformat->flags & AVFMT_GLOBALHEADER)
        c->enc->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
    {
        char buf[16];
        snprintf(buf, sizeof buf, "%d", crf > 0 ? crf : 23);
        av_opt_set(c->enc->priv_data, "crf", buf, 0);
        av_opt_set(c->enc->priv_data, "preset",
                   preset && preset[0] ? preset : "veryfast", 0);
    }
    if (avcodec_open2(c->enc, codec, NULL) < 0) goto fail;
    if (avcodec_parameters_from_context(c->stream->codecpar, c->enc) < 0) goto fail;
    c->stream->time_base = c->enc->time_base;
    c->stream->avg_frame_rate = c->enc->framerate;

    if (avio_open(&c->fmt->pb, path, AVIO_FLAG_WRITE) < 0) goto fail;
    if (avformat_write_header(c->fmt, NULL) < 0) goto fail;
    c->header_written = 1;

    c->frame = av_frame_alloc();
    c->pkt = av_packet_alloc();
    if (!c->frame || !c->pkt) goto fail;
    c->frame->format = AV_PIX_FMT_YUV420P;
    c->frame->width = w;
    c->frame->height = h;
    if (av_frame_get_buffer(c->frame, 0) < 0) goto fail;

    c->sws = sws_getContext(w, h, AV_PIX_FMT_BGR24, w, h, AV_PIX_FMT_YUV420P,
                            SWS_BILINEAR, NULL, NULL, NULL);
    if (!c->sws) goto fail;
    return c;
fail:
    ctx_free(c);
    return NULL;
}

static int drain(H264Ctx *c) {
    for (;;) {
        int r = avcodec_receive_packet(c->enc, c->pkt);
        if (r == AVERROR(EAGAIN) || r == AVERROR_EOF) return 0;
        if (r < 0) return r;
        if (c->pkt->duration == 0)
            c->pkt->duration = 1; /* one frame period, else the container
                                     under-reports total duration and players
                                     read a wrong frame rate */
        av_packet_rescale_ts(c->pkt, c->enc->time_base, c->stream->time_base);
        c->pkt->stream_index = c->stream->index;
        r = av_interleaved_write_frame(c->fmt, c->pkt);
        av_packet_unref(c->pkt);
        if (r < 0) return r;
    }
}

int curate_h264_write(void *ctx, const uint8_t *bgr) {
    H264Ctx *c = ctx;
    if (!c || !bgr) return -1;
    if (av_frame_make_writable(c->frame) < 0) return -2;
    const uint8_t *src[1] = {bgr};
    const int stride[1] = {3 * c->width};
    sws_scale(c->sws, src, stride, 0, c->height, c->frame->data, c->frame->linesize);
    /* one tick of time_base (1000/(fps*1000)) is exactly one frame period */
    c->frame->pts = c->next_pts;
    c->next_pts += 1;
    if (avcodec_send_frame(c->enc, c->frame) < 0) return -3;
    return drain(c);
}

int curate_h264_close(void *ctx) {
    H264Ctx *c = ctx;
    if (!c) return -1;
    int rc = 0;
    if (c->header_written) {
        avcodec_send_frame(c->enc, NULL); /* flush */
        rc = drain(c);
        if (av_write_trailer(c->fmt) < 0 && rc == 0) rc = -4;
    }
    ctx_free(c);
    return rc;
}
