/* Codec motion-vector extraction over libavcodec (export_mvs).
 *
 * Equivalent capability of the reference's motion-vector backend
 * (cosmos_curate/pipelines/video/filtering/motion/motion_vector_backend.py
 * — PyAV/ffmpeg `export_mvs` side data feeding global-mean and
 * per-patch-min motion scores): the decoder exports per-block motion
 * vectors for inter-coded frames (mpeg4 AND h264 — whatever the clip was
 * transcoded with), and this binding aggregates them into a per-frame
 * grid of mean |mv| in pixels. Python (video/motion_vectors.py) turns the
 * grid into filter scores; frames without side data (intra frames) are
 * flagged so callers can exclude them.
 *
 * API (ctypes, cosmos_curate_tpu/native/__init__.py load_mv):
 *   curate_mv_field(path, grid, out_field, out_has, max_frames,
 *                   out_w, out_h) -> n_frames (<0 on error)
 *     out_field: float32 [max_frames][grid][grid] mean |mv| per cell
 *     out_has:   uint8   [max_frames] 1 when the frame carried MVs
 */

#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/motion_vector.h>
#include <math.h>
#include <stdlib.h>
#include <string.h>

struct cell_acc {
    double sum;  /* |mv| weighted by overlap area */
    double area; /* total overlap area */
};

static void frame_cells(const AVFrame *frame, int grid, int w, int h,
                        float *out_cells, unsigned char *out_has) {
    AVFrameSideData *sd =
        av_frame_get_side_data(frame, AV_FRAME_DATA_MOTION_VECTORS);
    memset(out_cells, 0, (size_t)grid * grid * sizeof(float));
    *out_has = 0;
    if (!sd || w <= 0 || h <= 0)
        return;
    *out_has = 1;
    struct cell_acc *acc = calloc((size_t)grid * grid, sizeof(*acc));
    if (!acc)
        return;
    const AVMotionVector *mvs = (const AVMotionVector *)sd->data;
    size_t n = sd->size / sizeof(*mvs);
    for (size_t i = 0; i < n; i++) {
        const AVMotionVector *mv = &mvs[i];
        double scale = mv->motion_scale > 0 ? (double)mv->motion_scale : 1.0;
        double mag = hypot(mv->motion_x / scale, mv->motion_y / scale);
        /* area-weighted spread over every cell the BLOCK overlaps:
         * (dst_x, dst_y) is the block center and blocks (16x16 MBs) can be
         * coarser than the cell grid — center-point binning would leave
         * whole cell rows without vectors and fake a static patch */
        double x0 = mv->dst_x - mv->w / 2.0, x1 = x0 + mv->w;
        double y0 = mv->dst_y - mv->h / 2.0, y1 = y0 + mv->h;
        double cw = (double)w / grid, ch = (double)h / grid;
        int cx0 = (int)(x0 / cw), cx1 = (int)((x1 - 1e-9) / cw);
        int cy0 = (int)(y0 / ch), cy1 = (int)((y1 - 1e-9) / ch);
        if (cx0 < 0) cx0 = 0;
        if (cy0 < 0) cy0 = 0;
        if (cx1 >= grid) cx1 = grid - 1;
        if (cy1 >= grid) cy1 = grid - 1;
        for (int cy = cy0; cy <= cy1; cy++) {
            for (int cx = cx0; cx <= cx1; cx++) {
                double ox = fmin(x1, (cx + 1) * cw) - fmax(x0, cx * cw);
                double oy = fmin(y1, (cy + 1) * ch) - fmax(y0, cy * ch);
                if (ox <= 0 || oy <= 0)
                    continue;
                acc[cy * grid + cx].sum += mag * ox * oy;
                acc[cy * grid + cx].area += ox * oy;
            }
        }
    }
    for (int c = 0; c < grid * grid; c++)
        /* cells with no covering vectors stay 0: codecs skip static
         * blocks, which IS the "no motion" signal the filter keys on */
        out_cells[c] =
            acc[c].area > 0 ? (float)(acc[c].sum / acc[c].area) : 0.0f;
    free(acc);
}

int curate_mv_field(const char *path, int grid, float *out_field,
                    unsigned char *out_has, int max_frames, int *out_w,
                    int *out_h) {
    AVFormatContext *fmt = NULL;
    AVCodecContext *ctx = NULL;
    AVPacket *pkt = NULL;
    AVFrame *frame = NULL;
    AVDictionary *opts = NULL;
    int nframes = 0, ret = -1;

    av_log_set_level(AV_LOG_ERROR);
    if (grid <= 0 || max_frames <= 0)
        return -1;
    if (avformat_open_input(&fmt, path, NULL, NULL) < 0)
        return -1;
    if (avformat_find_stream_info(fmt, NULL) < 0)
        goto done;
    const AVCodec *dec = NULL;
    int vstream = av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, &dec, 0);
    if (vstream < 0 || !dec)
        goto done;
    ctx = avcodec_alloc_context3(dec);
    if (!ctx || avcodec_parameters_to_context(
                    ctx, fmt->streams[vstream]->codecpar) < 0)
        goto done;
    av_dict_set(&opts, "flags2", "+export_mvs", 0);
    if (avcodec_open2(ctx, dec, &opts) < 0)
        goto done;
    pkt = av_packet_alloc();
    frame = av_frame_alloc();
    if (!pkt || !frame)
        goto done;

    while (nframes < max_frames && av_read_frame(fmt, pkt) >= 0) {
        if (pkt->stream_index == vstream &&
            avcodec_send_packet(ctx, pkt) >= 0) {
            while (nframes < max_frames &&
                   avcodec_receive_frame(ctx, frame) >= 0) {
                frame_cells(frame, grid, ctx->width, ctx->height,
                            out_field + (size_t)nframes * grid * grid,
                            out_has + nframes);
                nframes++;
            }
        }
        av_packet_unref(pkt);
    }
    /* drain the decoder */
    if (nframes < max_frames && avcodec_send_packet(ctx, NULL) >= 0) {
        while (nframes < max_frames &&
               avcodec_receive_frame(ctx, frame) >= 0) {
            frame_cells(frame, grid, ctx->width, ctx->height,
                        out_field + (size_t)nframes * grid * grid,
                        out_has + nframes);
            nframes++;
        }
    }
    if (out_w) *out_w = ctx->width;
    if (out_h) *out_h = ctx->height;
    ret = nframes;

done:
    av_dict_free(&opts);
    if (frame) av_frame_free(&frame);
    if (pkt) av_packet_free(&pkt);
    if (ctx) avcodec_free_context(&ctx);
    if (fmt) avformat_close_input(&fmt);
    return ret;
}
