"""LazyData: a container for large payloads that defers materialization.

Equivalent capability of the reference's ``LazyData[T]`` state machine
(cosmos_curate/core/utils/data/lazy_data.py:16-70): a payload can be

- **inline** — held in memory, travels with the task through the object store
  via zero-copy pickle (PEP 574 out-of-band buffers);
- **stored** — spilled to a storage path; only the path pickles, and
  consumers fetch on first access;
- **absent** — already consumed/cleared to free memory.

The reference's split-field ObjectRef mode is deliberately not reproduced
(it documents a Ray ownership-GC root cause at lazy_data.py:50-70); our
engine's shared-memory object store makes task-level zero-copy the fast path.
"""

from __future__ import annotations

import pickle
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class LazyData(Generic[T]):
    """Holds ``bytes | numpy``-like payloads lazily.

    Not thread-safe; tasks are owned by one worker at a time by design.
    """

    __slots__ = ("_value", "_path", "_loader")

    def __init__(
        self,
        value: T | None = None,
        *,
        path: str | None = None,
        loader: Callable[[str], T] | None = None,
    ) -> None:
        if value is None and path is None:
            raise ValueError("LazyData needs an inline value or a stored path")
        self._value = value
        self._path = path
        self._loader = loader

    # -- state ------------------------------------------------------------
    @property
    def is_inline(self) -> bool:
        return self._value is not None

    @property
    def is_stored(self) -> bool:
        return self._path is not None

    @property
    def path(self) -> str | None:
        return self._path

    # -- access -----------------------------------------------------------
    def get(self) -> T:
        """Materialize: returns the inline value or loads from storage."""
        if self._value is not None:
            return self._value
        if self._path is None:
            raise RuntimeError("LazyData already cleared")
        loader = self._loader or _default_loader
        self._value = loader(self._path)
        return self._value

    def store(self, path: str, writer: Callable[[str, T], None] | None = None) -> None:
        """Spill the inline value to ``path`` and drop it from memory.

        Non-bytes values use pickle by default and therefore require a
        ``.pkl`` path so the default loader round-trips them."""
        if self._value is None:
            raise RuntimeError("nothing inline to store")
        if (
            writer is None
            and self._loader is None
            and not isinstance(self._value, (bytes, bytearray, memoryview))
            and not path.endswith(".pkl")
        ):
            raise ValueError(
                f"default spill of a {type(self._value).__name__} uses pickle; "
                f"use a '.pkl' path or pass an explicit writer+loader ({path!r})"
            )
        (writer or _default_writer)(path, self._value)
        self._path = path
        self._value = None

    def clear(self) -> None:
        """Drop the in-memory copy (keeps the stored path, if any)."""
        self._value = None

    def nbytes(self) -> int:
        v = self._value
        if v is None:
            return 0
        if isinstance(v, (bytes, bytearray, memoryview)):
            return len(v)
        return getattr(v, "nbytes", 0)

    # -- pickle: stored form travels as just the path (+loader) ------------
    # Custom loaders must be picklable (module-level functions, not lambdas).
    def __reduce__(self):
        return (_rebuild, (self._value, self._path, self._loader))

    def __repr__(self) -> str:
        state = "inline" if self.is_inline else ("stored" if self.is_stored else "cleared")
        return f"LazyData<{state}, {self.nbytes()}B, path={self._path!r}>"


def _rebuild(value, path, loader):
    return LazyData(value=value, path=path, loader=loader)


def _default_loader(path: str):
    from cosmos_curate_tpu.storage.client import read_bytes

    data = read_bytes(path)
    if path.endswith(".pkl"):
        return pickle.loads(data)
    return data


def _default_writer(path: str, value) -> None:
    from cosmos_curate_tpu.storage.client import write_bytes

    if isinstance(value, (bytes, bytearray, memoryview)):
        write_bytes(path, bytes(value))
    else:
        write_bytes(path, pickle.dumps(value, protocol=5))
