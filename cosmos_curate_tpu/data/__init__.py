from cosmos_curate_tpu.data.lazy import LazyData
from cosmos_curate_tpu.data.model import (
    Clip,
    ClipStats,
    ShardPipeTask,
    SplitPipeTask,
    Video,
    VideoMetadata,
    Window,
)

__all__ = [
    "Clip",
    "ClipStats",
    "LazyData",
    "ShardPipeTask",
    "SplitPipeTask",
    "Video",
    "VideoMetadata",
    "Window",
]
