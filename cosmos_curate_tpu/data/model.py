"""The video-curation data model: the payload flowing through every stage.

Equivalent capability of the reference's data model
(cosmos_curate/pipelines/video/utils/data_model.py: ``Window``:155,
``Clip``:195, ``ClipStats``:346, ``VideoMetadata``:393, ``Video``:414,
``SplitPipeTask``:691, ``ShardPipeTask``:837), re-designed TPU-first:

- decoded frames are numpy ``uint8 [T, H, W, 3]`` arrays keyed by a
  ``FrameExtractionSignature`` so a CPU prep stage can extract once and many
  device stages reuse;
- embeddings are plain numpy ``float32`` (device arrays never travel between
  stages — host arrays do, and each TPU stage shards them onto its mesh);
- per-item errors are recorded on the object (``Clip.errors``), never thrown
  across the pipeline, so one bad video cannot kill a run (reference
  containment model, SURVEY.md §5).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from cosmos_curate_tpu.core.tasks import PipelineTask, estimate_major_size

_UUID_NAMESPACE = uuid.UUID("8c5aa64e-25f1-44f3-b9a2-3cfb0c1a75d1")


def deterministic_id(*parts: str) -> uuid.UUID:
    """Stable uuid5 chain over string parts (reference uses uuid5 chains from
    session + span, clip_extraction_stages.py:554) so re-runs produce
    identical clip ids and resume can dedupe."""
    u = _UUID_NAMESPACE
    for p in parts:
        u = uuid.uuid5(u, p)
    return u


@dataclass(frozen=True)
class FrameExtractionSignature:
    """Key for cached frame extractions: policy + rate."""

    policy: str = "fps"  # "fps" | "all" | "first_middle_last"
    target_fps: float = 1.0

    def key(self) -> str:
        return f"{self.policy}-{self.target_fps:g}"


@dataclass
class VideoMetadata:
    """Probe results for a source video."""

    width: int = 0
    height: int = 0
    fps: float = 0.0
    num_frames: int = 0
    duration_s: float = 0.0
    codec: str = ""
    pixel_format: str = ""
    bitrate_kbps: float = 0.0
    size_bytes: int = 0

    @property
    def is_valid(self) -> bool:
        return self.width > 0 and self.height > 0 and self.num_frames > 0


@dataclass
class Window:
    """A contiguous frame window of a clip, the captioning unit
    (256-frame windows by default, windowing_utils.py:53 in the reference)."""

    start_frame: int = 0
    end_frame: int = 0
    mp4_bytes: bytes | None = None
    frames: np.ndarray | None = None  # uint8 [T, H, W, 3]
    # sampling rate of `frames` in source-time fps (temporal m-rope scaling)
    frame_fps: float | None = None
    caption: dict[str, str] = field(default_factory=dict)  # prompt_variant -> text
    enhanced_caption: dict[str, str] = field(default_factory=dict)
    t5_embedding: np.ndarray | None = None
    model_inputs: dict[str, Any] = field(default_factory=dict)
    errors: dict[str, str] = field(default_factory=dict)

    @property
    def num_frames(self) -> int:
        return self.end_frame - self.start_frame

    def release_payloads(self) -> None:
        self.mp4_bytes = None
        self.frames = None
        self.model_inputs.clear()


@dataclass
class ClipStats:
    """Aggregated accounting over clips, merged into the run summary."""

    num_clips: int = 0
    num_filtered_by_motion: int = 0
    num_filtered_by_aesthetic: int = 0
    num_filtered_by_text: int = 0
    num_filtered_by_semantic: int = 0
    num_filtered_by_dedup: int = 0
    num_transcoded: int = 0
    num_with_embeddings: int = 0
    num_with_captions: int = 0
    num_with_webp: int = 0
    total_clip_duration_s: float = 0.0
    max_clip_duration_s: float = 0.0

    def combine(self, other: "ClipStats") -> None:
        self.num_clips += other.num_clips
        self.num_filtered_by_motion += other.num_filtered_by_motion
        self.num_filtered_by_aesthetic += other.num_filtered_by_aesthetic
        self.num_filtered_by_text += other.num_filtered_by_text
        self.num_filtered_by_semantic += other.num_filtered_by_semantic
        self.num_filtered_by_dedup += other.num_filtered_by_dedup
        self.num_transcoded += other.num_transcoded
        self.num_with_embeddings += other.num_with_embeddings
        self.num_with_captions += other.num_with_captions
        self.num_with_webp += other.num_with_webp
        self.total_clip_duration_s += other.total_clip_duration_s
        self.max_clip_duration_s = max(self.max_clip_duration_s, other.max_clip_duration_s)


@dataclass
class Clip:
    """One shot-detected span of a source video and everything derived
    from it as it moves down the pipeline."""

    uuid: uuid.UUID = field(default_factory=uuid.uuid4)
    source_video: str = ""
    span: tuple[float, float] = (0.0, 0.0)  # seconds in source
    encoded_data: bytes | None = None  # transcoded mp4
    encoding_codec: str = ""
    # provenance recorded by the writer before encoded_data is freed
    # (video_span rows need geometry + content hash + the REAL written
    # destination after the pipeline ran)
    encoded_byte_size: int = 0
    encoded_sha256: str = ""
    encoded_url: str = ""
    # extraction-signature key -> uint8 [T, H, W, 3]
    extracted_frames: dict[str, np.ndarray] = field(default_factory=dict)
    # model name -> float32 embedding
    embeddings: dict[str, np.ndarray] = field(default_factory=dict)
    motion_score_global: float | None = None
    motion_score_per_patch_min: float | None = None
    aesthetic_score: float | None = None
    artificial_text_score: float | None = None
    semantic_pass: bool | None = None
    windows: list[Window] = field(default_factory=list)
    webp_preview: bytes | None = None
    # object tracks: list of tracks, each a list of per-frame dicts
    # ({frame, x, y, w, h, score}); produced by the tracking stage
    tracks: list[list[dict]] = field(default_factory=list)
    event_captions: list[str] = field(default_factory=list)  # parallel to tracks
    annotated_mp4: bytes | None = None
    filtered_by: str = ""  # which filter removed this clip ("" = kept)
    # set by incremental dedup: the indexed clip this one duplicates
    # (within eps cosine distance); empty = no duplicate found / not checked
    duplicate_of: str = ""
    errors: dict[str, str] = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return self.span[1] - self.span[0]

    @property
    def is_kept(self) -> bool:
        return not self.filtered_by

    def release_frames(self) -> None:
        self.extracted_frames.clear()

    def get_major_size(self) -> int:
        return estimate_major_size(self)


@dataclass
class Video:
    """A source video being split."""

    path: str = ""
    # camera label for multicam sessions (filename stem by convention);
    # empty for single-camera inputs
    camera: str = ""
    raw_bytes: bytes | None = None
    metadata: VideoMetadata = field(default_factory=VideoMetadata)
    clips: list[Clip] = field(default_factory=list)
    filtered_clips: list[Clip] = field(default_factory=list)
    num_total_clips: int = 0
    num_clip_chunks: int = 1
    clip_chunk_index: int = 0
    errors: dict[str, str] = field(default_factory=dict)

    def release_raw(self) -> None:
        self.raw_bytes = None

    @property
    def num_frames(self) -> int:
        return self.metadata.num_frames


@dataclass
class SplitPipeTask(PipelineTask):
    """Unit of work in the split-annotate pipeline: one video (or one chunk
    of its clips after dynamic re-chunking).

    Multi-camera sessions (reference docs/curator/design/MULTICAM.md):
    ``video`` is the PRIMARY camera — every single-camera stage (filters,
    embedding, captioning) keeps operating on it unchanged; time-aligned
    secondary cameras ride in ``aux_videos`` and are handled by the
    camera-aware stages (download, extraction, transcode, writer)."""

    video: Video = field(default_factory=Video)
    # secondary cameras, clips time-aligned with the primary's spans
    aux_videos: list[Video] = field(default_factory=list)
    # multicam session identity (the session directory name); empty for
    # single-camera tasks
    session_id: str = ""
    stage_perf: dict[str, float] = field(default_factory=dict)
    stats: ClipStats | None = None

    @property
    def videos(self) -> list[Video]:
        """All cameras, primary first."""
        return [self.video, *self.aux_videos]

    @property
    def is_multicam(self) -> bool:
        return bool(self.aux_videos)

    @property
    def weight(self) -> float:
        # Weight by content duration so the scheduler balances long videos.
        return max(1.0, self.video.metadata.duration_s / 60.0) * len(self.videos)

    @property
    def fraction(self) -> float:
        return 1.0 / max(1, self.video.num_clip_chunks)


@dataclass
class ShardPipeTask(PipelineTask):
    """Unit of work in the shard-dataset pipeline: a bucket of clip records
    destined for one webdataset tar."""

    bucket_key: str = ""
    clip_records: list[dict[str, Any]] = field(default_factory=list)
    output_path: str = ""
