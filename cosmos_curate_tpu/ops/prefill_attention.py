"""Chunked-prefill GQA attention over a KV cache as a Pallas TPU kernel.

The caption engine's prefill attends a chunk of T new tokens against the
slot cache (its own K/V already written at ``write_index``). The XLA path
materializes fp32 logits ``[B, Hkv, G, T, S]`` — at T=256, S=4096 that is
the HBM hot spot of long-prompt captioning (the reference leans on
FlashInfer prefill kernels via vLLM, SPEED_OF_LIGHT.md). This kernel
streams K/V blocks through VMEM with an online softmax:

- **cache-native layout**: reads ``[B, S, Hkv, D]`` directly and keeps GQA
  queries grouped (``[B, T, Hkv, G, D]``) so each KV byte is read once for
  all G grouped queries;
- **causality by absolute position**: query t's position is
  ``write_index + t`` (scalar-prefetched per row), so the SAME kernel
  serves bucket prefill (write_index=0) and later chunks of a chunked
  prefill (write_index>0) — matching DecoderLayer's mask semantics;
- **early exit**: K/V blocks entirely beyond the chunk's last causal
  position, or at/after the row's valid length, are skipped (`pl.when`).

Off-TPU the kernel runs in interpreter mode (CPU tests exercise the same
code path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _prefill_kernel(
    write_ref,
    kvlen_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    sm_scale,
    block_q,
    block_k,
    g,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    write = write_ref[b]
    kv_len = kvlen_ref[b]
    k_start = ki * block_k
    rows = block_q * g
    # last causal position any query in this q-tile can see
    last_pos = write + qi * block_q + block_q - 1

    @pl.when((k_start <= last_pos) & (k_start < kv_len))
    def _step():
        q = q_ref[0, :, 0].astype(jnp.float32).reshape(rows, q_ref.shape[-1])
        q = q * sm_scale
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, block_k]
        # row r is query (t_local = r // g); its absolute position is
        # write + qi*block_q + t_local
        t_local = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0) // g
        q_pos = write + qi * block_q + t_local
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 1)
        ok = (k_pos <= q_pos) & (k_pos < kv_len)
        s = jnp.where(ok, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, :, 0, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(ki == num_k - 1)
    def _finish():
        out = acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, :, 0] = out.reshape(block_q, g, o_ref.shape[-1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "block_q", "block_k", "interpret")
)
def prefill_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    write_index: jax.Array,
    kv_len: jax.Array,
    *,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """q: [B, T, Hkv, G, D] (a prefill chunk, GQA-grouped); k_cache/v_cache:
    [B, S, Hkv, D] with the chunk's K/V already written at ``write_index``;
    write_index/kv_len: [B]. Returns [B, T, Hkv, G, D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, t, hk, g, d = q.shape
    t_orig = t
    s = k_cache.shape[1]
    block_q = min(block_q, t)
    if t % block_q:
        pad = block_q - t % block_q
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
        t += pad
    block_k = min(block_k, s)
    if s % block_k:
        pad = block_k - s % block_k
        k_cache = jnp.pad(k_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        s += pad

    grid = (b, hk, t // block_q, s // block_k)
    kernel = functools.partial(
        _prefill_kernel, sm_scale=sm_scale, block_q=block_q, block_k=block_k, g=g
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, block_q, 1, g, d), lambda b_, h, qi, ki, *_: (b_, qi, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, 1, d), lambda b_, h, qi, ki, *_: (b_, ki, h, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, 1, d), lambda b_, h, qi, ki, *_: (b_, ki, h, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, 1, g, d), lambda b_, h, qi, ki, *_: (b_, qi, h, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q * g, d), jnp.float32),
                pltpu.VMEM((block_q * g, 128), jnp.float32),
                pltpu.VMEM((block_q * g, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, hk, g, d), q.dtype),
        interpret=interpret,
    )(write_index.astype(jnp.int32), kv_len.astype(jnp.int32), q, k_cache, v_cache)
    return out[:, :t_orig]
