"""Single-token GQA decode attention as a Pallas TPU kernel.

The caption engine's decode step is KV-cache-bandwidth-bound: one new token
per slot attends to the whole slot cache (reference leans on FlashInfer
decode kernels via vLLM, models/vllm_interface.py:543 /
SPEED_OF_LIGHT.md). This kernel streams K/V blocks through VMEM with an
online softmax and two decode-specific wins over the generic flash kernel:

- **no transpose/repeat**: operates directly on the cache layout
  ``[B, S, Hkv, D]`` (BlockSpec picks the head plane), and queries stay
  grouped ``[B, Hkv, G, D]`` so GQA reads each KV byte once;
- **early exit**: the per-row valid length is scalar-prefetched, and KV
  blocks at or beyond it are skipped entirely (`pl.when`) — decode cost
  follows the *actual* sequence length, not the padded cache size.

Off-TPU the kernel runs in interpreter mode (CPU tests exercise the same
code path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _decode_kernel(
    kvlen_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale, block_k, g_pad
):
    b = pl.program_id(0)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = kvlen_ref[b]
    k_start = ki * block_k

    @pl.when(k_start < kv_len)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [g_pad, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g_pad, block_k]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (g_pad, block_k), 1)
        s = jnp.where(k_pos < kv_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, :, 0, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "block_k", "interpret")
)
def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    kv_len: jax.Array,
    *,
    sm_scale: float | None = None,
    block_k: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """q: [B, Hkv, G, D] (one token per row, grouped GQA queries);
    k_cache/v_cache: [B, S, Hkv, D]; kv_len: [B] valid lengths (the new
    token's K/V already written). Returns [B, Hkv, G, D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, hk, g, d = q.shape
    s = k_cache.shape[1]
    block_k = min(block_k, s)
    if s % block_k:
        pad = block_k - s % block_k
        k_cache = jnp.pad(k_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        s += pad
    g_pad = max(8, g)  # sublane minimum
    if g_pad != g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, g_pad - g), (0, 0)))

    grid = (b, hk, s // block_k)
    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, block_k=block_k, g_pad=g_pad
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            # index maps receive the scalar-prefetch ref as a trailing arg
            in_specs=[
                pl.BlockSpec((1, 1, g_pad, d), lambda b_, h, ki, *_: (b_, h, 0, 0)),
                pl.BlockSpec((1, block_k, 1, d), lambda b_, h, ki, *_: (b_, ki, h, 0)),
                pl.BlockSpec((1, block_k, 1, d), lambda b_, h, ki, *_: (b_, ki, h, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, g_pad, d), lambda b_, h, ki, *_: (b_, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g_pad, d), jnp.float32),
                pltpu.VMEM((g_pad, 128), jnp.float32),
                pltpu.VMEM((g_pad, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hk, g_pad, d), q.dtype),
        interpret=interpret,
    )(kv_len.astype(jnp.int32), q, k_cache, v_cache)
    return out[:, :, :g]
