from cosmos_curate_tpu.ops.flash_attention import flash_attention
from cosmos_curate_tpu.ops.paged_attention import paged_attention, paged_head_attention

__all__ = ["flash_attention", "paged_attention", "paged_head_attention"]
