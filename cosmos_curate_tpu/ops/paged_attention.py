"""Paged GQA attention that reads the KV block pool through the block table.

vLLM's PagedAttention kernel (Kwon et al., SOSP 2023 — PAPERS.md) computes
attention directly against non-contiguous KV blocks: the kernel walks the
slot's block table and streams each physical block through on-chip memory.
PR 11 gave the caption engine the paged *pool* but kept gather-based
programs — every prefill chunk and every decode step materialized a
contiguous ``[L, n_slots, lane_length]`` copy of the whole KV working set
and scattered it back. This op deletes that copy:

- **table-driven BlockSpecs**: the block table is scalar-prefetched, so the
  Pallas index map resolves grid step ``j`` to physical pool block
  ``table[b, j]`` — the kernel reads pool pages in place, nothing is
  gathered;
- **logical positions from the table index**: table entry ``j`` covers
  logical positions ``[j*bs, (j+1)*bs)`` regardless of where the block
  lives in the pool, so masking is identical to the contiguous kernels;
- **early exit**: blocks at/after the row's valid length (and, for prefill,
  beyond the chunk's last causal position) are skipped with ``pl.when`` —
  fragmented tables cost nothing extra.

Off-TPU the default is NOT interpret-mode Pallas but a ``jax.lax``
reference that mirrors ``DecoderLayer``'s XLA attention lines exactly
(same einsums, same mask construction, same fp32 softmax), so the engine's
byte-identical parity contract (tests/models/test_paged_kv.py) holds on
CPU: the reference gathers per-layer blocks for the einsum but never
scatters a view back. ``CURATE_PAGED_KERNEL=1|0`` forces the Pallas /
reference path regardless of platform (interpret mode fills in off-TPU).

``paged_head_attention`` wraps the op in a ``shard_map`` over the model
mesh axis: KV pool and queries shard over heads, block tables and lengths
replicate — the tensor-parallel form traced by shardcheck's
``vlm-paged-head-attention`` contract (analysis/shard_check.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def use_paged_kernel() -> bool:
    """Platform/env gate for the Pallas path (mirrors ``_flash_gate``):
    ``CURATE_PAGED_KERNEL=1`` forces the kernel, ``=0`` forces the XLA
    reference, otherwise the kernel runs on real TPUs only."""
    env = os.environ.get("CURATE_PAGED_KERNEL")
    if env is not None:
        return env == "1"
    return jax.devices()[0].platform == "tpu"


def _paged_reference(q, pool_k, pool_v, tables, write_index, kv_len, *, layer_index, sm_scale):
    """Byte-parity XLA path: gathers the slot's blocks for the einsum (no
    scatter-back) and then replays DecoderLayer's reference attention lines
    verbatim — same primitive sequence on the same shapes/values, so CPU
    outputs are bit-equal to the gather programs."""
    b, t, hk, g, d = q.shape
    nbl = tables.shape[1]
    bs = pool_k.shape[2]
    s = nbl * bs
    new_k = pool_k[layer_index][tables].reshape(b, s, hk, d)
    new_v = pool_v[layer_index][tables].reshape(b, s, hk, d)
    qg = q * sm_scale
    logits = jnp.einsum(
        "btkgd,bskd->bkgts", qg.astype(jnp.float32), new_k.astype(jnp.float32)
    )
    k_pos = jnp.arange(s)[None, None, None, None, :]
    q_seq = write_index[:, None] + jnp.arange(t)[None, :]
    causal = k_pos <= q_seq[:, None, None, :, None]
    written = k_pos < kv_len[:, None, None, None, None]
    logits = jnp.where(causal & written, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bkgts,bskd->btkgd", probs.astype(q.dtype), new_v)


def _paged_decode_kernel(
    kvlen_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale, bs, g_pad
):
    b = pl.program_id(0)
    ji = pl.program_id(2)
    num_j = pl.num_programs(2)

    @pl.when(ji == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = kvlen_ref[b]
    # table entry ji covers LOGICAL positions [ji*bs, (ji+1)*bs) — the
    # physical pool block was picked by the BlockSpec index map
    k_start = ji * bs

    @pl.when(k_start < kv_len)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [g_pad, d]
        k = k_ref[0, 0, :, 0, :].astype(jnp.float32)  # [bs, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g_pad, bs]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (g_pad, bs), 1)
        s = jnp.where(k_pos < kv_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0, :, 0, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(ji == num_j - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _paged_prefill_kernel(
    write_ref,
    kvlen_ref,
    tbl_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    sm_scale,
    block_q,
    bs,
    g,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ji = pl.program_id(3)
    num_j = pl.num_programs(3)

    @pl.when(ji == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    write = write_ref[b]
    kv_len = kvlen_ref[b]
    k_start = ji * bs
    rows = block_q * g
    last_pos = write + qi * block_q + block_q - 1

    @pl.when((k_start <= last_pos) & (k_start < kv_len))
    def _step():
        q = q_ref[0, :, 0].astype(jnp.float32).reshape(rows, q_ref.shape[-1])
        q = q * sm_scale
        k = k_ref[0, 0, :, 0, :].astype(jnp.float32)  # [bs, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rows, bs]
        t_local = jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) // g
        q_pos = write + qi * block_q + t_local
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        ok = (k_pos <= q_pos) & (k_pos < kv_len)
        s = jnp.where(ok, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0, :, 0, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(ji == num_j - 1)
    def _finish():
        out = acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, :, 0] = out.reshape(block_q, g, o_ref.shape[-1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("layer_index", "sm_scale", "interpret")
)
def _paged_decode(q, pool_k, pool_v, tables, kv_len, *, layer_index, sm_scale, interpret):
    """q: [B, Hkv, G, D]; pools: [L, NB, bs, Hkv, D]; tables: [B, nbl]."""
    b, hk, g, d = q.shape
    nbl = tables.shape[1]
    bs = pool_k.shape[2]
    g_pad = max(8, g)  # sublane minimum
    if g_pad != g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, g_pad - g), (0, 0)))

    grid = (b, hk, nbl)
    kernel = functools.partial(_paged_decode_kernel, sm_scale=sm_scale, bs=bs, g_pad=g_pad)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            # the table ref arrives as a trailing index-map arg: grid step
            # ji reads physical pool block tbl[b, ji] in place
            in_specs=[
                pl.BlockSpec((1, 1, g_pad, d), lambda b_, h, ji, kvlen, tbl: (b_, h, 0, 0)),
                pl.BlockSpec(
                    (1, 1, bs, 1, d),
                    lambda b_, h, ji, kvlen, tbl: (layer_index, tbl[b_, ji], 0, h, 0),
                ),
                pl.BlockSpec(
                    (1, 1, bs, 1, d),
                    lambda b_, h, ji, kvlen, tbl: (layer_index, tbl[b_, ji], 0, h, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, g_pad, d), lambda b_, h, ji, kvlen, tbl: (b_, h, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((g_pad, d), jnp.float32),
                pltpu.VMEM((g_pad, 128), jnp.float32),
                pltpu.VMEM((g_pad, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hk, g_pad, d), q.dtype),
        interpret=interpret,
    )(kv_len.astype(jnp.int32), tables.astype(jnp.int32), q, pool_k, pool_v)
    return out[:, :, :g]


@functools.partial(
    jax.jit, static_argnames=("layer_index", "sm_scale", "block_q", "interpret")
)
def _paged_prefill(
    q, pool_k, pool_v, tables, write_index, kv_len, *, layer_index, sm_scale, block_q, interpret
):
    """q: [B, T, Hkv, G, D]; pools: [L, NB, bs, Hkv, D]; tables: [B, nbl]."""
    b, t, hk, g, d = q.shape
    t_orig = t
    nbl = tables.shape[1]
    bs = pool_k.shape[2]
    block_q = min(block_q, t)
    if t % block_q:
        pad = block_q - t % block_q
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0), (0, 0)))
        t += pad

    grid = (b, hk, t // block_q, nbl)
    kernel = functools.partial(
        _paged_prefill_kernel, sm_scale=sm_scale, block_q=block_q, bs=bs, g=g
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, block_q, 1, g, d),
                    lambda b_, h, qi, ji, write, kvlen, tbl: (b_, qi, h, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, bs, 1, d),
                    lambda b_, h, qi, ji, write, kvlen, tbl: (
                        layer_index,
                        tbl[b_, ji],
                        0,
                        h,
                        0,
                    ),
                ),
                pl.BlockSpec(
                    (1, 1, bs, 1, d),
                    lambda b_, h, qi, ji, write, kvlen, tbl: (
                        layer_index,
                        tbl[b_, ji],
                        0,
                        h,
                        0,
                    ),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, 1, g, d),
                lambda b_, h, qi, ji, write, kvlen, tbl: (b_, qi, h, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q * g, d), jnp.float32),
                pltpu.VMEM((block_q * g, 128), jnp.float32),
                pltpu.VMEM((block_q * g, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, hk, g, d), q.dtype),
        interpret=interpret,
    )(
        write_index.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        tables.astype(jnp.int32),
        q,
        pool_k,
        pool_v,
    )
    return out[:, :t_orig]


def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    write_index: jax.Array,
    kv_len: jax.Array,
    *,
    layer_index: int = 0,
    sm_scale: float | None = None,
    block_q: int = 128,
    use_kernel: bool | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Attention straight out of the paged KV pool, no gathered working set.

    q: ``[B, T, Hkv, G, D]`` UNSCALED grouped queries (this op applies
    ``sm_scale`` so the reference path matches DecoderLayer bitwise);
    pool_k/pool_v: the full block pools ``[L, NB, bs, Hkv, D]`` with the
    chunk's K/V already written through the table; tables: ``[B, nbl]``
    logical-to-physical block ids; write_index/kv_len: ``[B]``. Serves both
    decode (T=1) and chunked prefill (T>1). Returns ``[B, T, Hkv, G, D]``.

    ``use_kernel=None`` resolves via :func:`use_paged_kernel` (env override,
    else TPU-only); the off-kernel path is the byte-parity XLA reference.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_kernel is None:
        use_kernel = use_paged_kernel()
    if not use_kernel:
        return _paged_reference(
            q, pool_k, pool_v, tables, write_index, kv_len,
            layer_index=layer_index, sm_scale=sm_scale,
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if q.shape[1] == 1:
        out = _paged_decode(
            q[:, 0], pool_k, pool_v, tables, kv_len,
            layer_index=layer_index, sm_scale=sm_scale, interpret=interpret,
        )
        return out[:, None]
    return _paged_prefill(
        q, pool_k, pool_v, tables, write_index, kv_len,
        layer_index=layer_index, sm_scale=sm_scale, block_q=block_q, interpret=interpret,
    )


def paged_head_attention(
    mesh,
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    write_index: jax.Array,
    kv_len: jax.Array,
    *,
    layer_index: int = 0,
    sm_scale: float | None = None,
    block_q: int = 128,
    use_kernel: bool | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Head-parallel paged attention over the model mesh axis.

    Queries, KV pools, and the output shard on their ``Hkv`` dimension over
    ``parallel/axes.MODEL``; block tables and lengths replicate (every shard
    walks the same table against its own head plane — attention is
    embarrassingly parallel over KV heads). Accepts an ``AbstractMesh`` so
    shardcheck's ``vlm-paged-head-attention`` contract traces this call
    site device-free. On a mesh without the model axis (or extent 1) the
    computation is identical to :func:`paged_attention` bit-for-bit.
    """
    from jax.sharding import PartitionSpec as P

    from cosmos_curate_tpu.parallel.axes import MODEL
    from cosmos_curate_tpu.parallel.sharding import shard_map

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis = MODEL if MODEL in mesh.axis_names else None
    qspec = P(None, None, axis, None, None)  # [B, T, Hkv, G, D]
    pspec = P(None, None, None, axis, None)  # [L, NB, bs, Hkv, D]
    fn = functools.partial(
        paged_attention,
        layer_index=layer_index,
        sm_scale=sm_scale,
        block_q=block_q,
        use_kernel=use_kernel,
        interpret=interpret,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qspec, pspec, pspec, P(None, None), P(None), P(None)),
        out_specs=qspec,
    )(q, pool_k, pool_v, tables, write_index, kv_len)
