"""Flash attention as a Pallas TPU kernel.

The hot op of every transformer stage (ViT towers, the VLM captioner, the
T5-class encoder). Standard flash-attention scheme (public technique):
tile Q into ``block_q`` rows and stream K/V tiles of ``block_k`` through
VMEM, maintaining an online softmax (running max / normalizer / accumulator
in VMEM scratch) so the full ``S x S`` score matrix never materializes in
HBM — attention becomes matmul-bound on the MXU instead of HBM-bound.

Grid: ``(batch x heads, q_blocks, kv_blocks)`` with the kv dimension
innermost (TPU pallas grids iterate sequentially, so scratch carries the
running state across kv steps). Causal blocks strictly above the diagonal
are skipped entirely (`pl.when`), halving causal FLOPs.

Off-TPU the kernel runs in interpreter mode so the same code path is
exercised by CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale, causal, seq_len, block_q, block_k
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip kv blocks strictly above the diagonal
    q_start = qi * block_q
    k_start = ki * block_k
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len  # padded tail keys contribute nothing
        if causal:
            mask &= k_pos <= q_pos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """q/k/v: [B, H, S, D] (self-attention lengths equal) -> [B, H, S, D].

    S is padded to the block size internally; padded keys are masked, padded
    query rows are sliced off. D should be a multiple of 128 for peak MXU
    utilization (works regardless).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, h, s, d = q.shape
    block_q = min(block_q, max(8, s))
    block_k = min(block_k, max(8, s))
    s_pad = ((s + block_q - 1) // block_q) * block_q
    s_pad = ((s_pad + block_k - 1) // block_k) * block_k

    def prep(x):
        x = x.reshape(b * h, s, d)
        if s_pad != s:
            x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0)))
        return x

    qf, kf, vf = prep(q), prep(k), prep(v)
    grid = (b * h, s_pad // block_q, s_pad // block_k)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=sm_scale,
        causal=causal,
        seq_len=s,
        block_q=block_q,
        block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :s].reshape(b, h, s, d)
