"""Per-tenant SLO tracking for the job service.

The admission layer (service/admission.py) decides WHO runs; this module
answers whether the service is honoring its promises to each tenant once
they do: queue wait (pending → running), run duration (running → done), and
success rate over a rolling window of terminal outcomes. Targets are
configured per deployment (``serve --slo-*`` knobs); a breach increments
``service_slo_breaches_total{tenant,kind}`` and is journaled against the
job, and ``GET /v1/slo`` reports every tenant's observed numbers against
the targets — the page-worthy view an operator (or an autoscaler) reads.

Pure data structure like AdmissionController: no IO, no clocks of its own
(callers pass the measured values), driven from the service event loop.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SLO_KINDS = ("queue_wait", "run_duration", "success_rate")

# a success-rate verdict needs a minimum sample before it can breach —
# one failed first job is not a 0% success rate worth paging on
MIN_OUTCOMES_FOR_RATE = 5


@dataclass(frozen=True)
class SloConfig:
    """Targets; 0 disables a dimension (the default — SLOs are opt-in)."""

    queue_wait_s: float = 0.0  # max acceptable pending→running wait
    run_duration_s: float = 0.0  # max acceptable running→terminal duration
    success_rate: float = 0.0  # min fraction of done outcomes, in (0, 1]
    window: int = 100  # rolling terminal-outcome window per tenant

    @property
    def enabled(self) -> bool:
        return bool(self.queue_wait_s or self.run_duration_s or self.success_rate)


@dataclass
class _TenantStats:
    jobs: int = 0
    queue_wait_sum_s: float = 0.0
    queue_wait_max_s: float = 0.0
    dispatches: int = 0
    duration_sum_s: float = 0.0
    duration_max_s: float = 0.0
    completed: int = 0
    outcomes: deque = field(default_factory=lambda: deque(maxlen=100))
    breaches: dict = field(default_factory=lambda: {k: 0 for k in SLO_KINDS})


class SloTracker:
    """Folds dispatch/terminal observations per tenant; returns breaches.

    Bounded: tenants are capped by the admission controller's max_tenants
    upstream, so per-tenant state here cannot grow unboundedly either."""

    def __init__(self, config: SloConfig | None = None) -> None:
        self.config = config or SloConfig()
        self._tenants: dict[str, _TenantStats] = {}

    def _stats(self, tenant: str) -> _TenantStats:
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantStats(
                outcomes=deque(maxlen=max(1, self.config.window))
            )
        return st

    # ------------------------------------------------------------------
    def observe_dispatch(self, tenant: str, wait_s: float) -> list[str]:
        """One pending→running transition. Returns the breached kinds."""
        st = self._stats(tenant)
        st.dispatches += 1
        st.queue_wait_sum_s += max(0.0, wait_s)
        st.queue_wait_max_s = max(st.queue_wait_max_s, wait_s)
        cfg = self.config
        if cfg.queue_wait_s and wait_s > cfg.queue_wait_s:
            st.breaches["queue_wait"] += 1
            return ["queue_wait"]
        return []

    def observe_terminal(
        self, tenant: str, state: str, duration_s: float | None
    ) -> list[str]:
        """One terminal transition (done/failed/dead_lettered/terminated).
        Returns the breached kinds (run_duration and/or success_rate)."""
        st = self._stats(tenant)
        st.jobs += 1
        breached: list[str] = []
        cfg = self.config
        if duration_s is not None:
            st.duration_sum_s += max(0.0, duration_s)
            st.duration_max_s = max(st.duration_max_s, duration_s)
            if cfg.run_duration_s and state == "done" and duration_s > cfg.run_duration_s:
                # only successful runs judge duration: a job that died in
                # 2 s must not pass (nor a terminated one fail) the
                # duration SLO
                st.breaches["run_duration"] += 1
                breached.append("run_duration")
        # operator terminations are excluded from the success window: the
        # tenant asked for the kill, the service didn't fail them
        if state != "terminated":
            st.outcomes.append(1 if state == "done" else 0)
            st.completed += 1 if state == "done" else 0
            if cfg.success_rate and len(st.outcomes) >= MIN_OUTCOMES_FOR_RATE:
                rate = sum(st.outcomes) / len(st.outcomes)
                if rate < cfg.success_rate:
                    st.breaches["success_rate"] += 1
                    breached.append("success_rate")
        return breached

    # ------------------------------------------------------------------
    def report(self) -> dict:
        """The ``/v1/slo`` payload: targets + per-tenant observed numbers
        and breach counts."""
        cfg = self.config
        tenants = {}
        for tenant, st in sorted(self._tenants.items()):
            rate = (
                round(sum(st.outcomes) / len(st.outcomes), 4)
                if st.outcomes
                else None
            )
            tenants[tenant] = {
                "queue_wait": {
                    "mean_s": round(st.queue_wait_sum_s / st.dispatches, 3)
                    if st.dispatches
                    else 0.0,
                    "max_s": round(st.queue_wait_max_s, 3),
                    "dispatches": st.dispatches,
                    "breaches": st.breaches["queue_wait"],
                },
                "run_duration": {
                    "mean_s": round(st.duration_sum_s / st.jobs, 3) if st.jobs else 0.0,
                    "max_s": round(st.duration_max_s, 3),
                    "breaches": st.breaches["run_duration"],
                },
                "success_rate": {
                    "rate": rate,
                    "window": len(st.outcomes),
                    "completed": st.completed,
                    "breaches": st.breaches["success_rate"],
                },
                "terminal_jobs": st.jobs,
                "breaches_total": sum(st.breaches.values()),
            }
        return {
            "targets": {
                "queue_wait_s": cfg.queue_wait_s or None,
                "run_duration_s": cfg.run_duration_s or None,
                "success_rate": cfg.success_rate or None,
                "window": cfg.window,
            },
            "enabled": cfg.enabled,
            "tenants": tenants,
        }
