"""Job service: pipelines behind an async HTTP API.

Equivalent capability of the reference's NVCF service wrapper
(cosmos_curate/core/cf/nvcf_main.py:548-600 — FastAPI app with /health,
/v1/logs, /v1/progress, invoke/terminate, a one-pipeline-at-a-time lock
middleware:373, and request/progress/done files:102-223). Built on aiohttp
(fastapi is not in this image; the HTTP surface is identical):

  GET  /health                liveness + current job state
  POST /v1/invoke             {"pipeline": "split"|"dedup"|"shard", "args": {...}}
  GET  /v1/progress/{job_id}  job state + summary when done
  GET  /v1/logs/{job_id}      captured job log tail
  POST /v1/terminate/{job_id} best-effort cancel

One pipeline runs at a time (the lock); jobs execute in a subprocess so a
crashing pipeline never takes the service down, and termination is a clean
process kill.
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path

from aiohttp import web

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_PIPELINES = {"split", "dedup", "shard"}


@dataclass
class Job:
    job_id: str
    pipeline: str
    args: dict
    work_dir: Path
    proc: subprocess.Popen | None = None
    state: str = "pending"  # pending | running | done | failed | terminated
    started_s: float = field(default_factory=time.time)
    finished_s: float | None = None

    @property
    def log_path(self) -> Path:
        return self.work_dir / "job.log"

    @property
    def summary_path(self) -> Path:
        return self.work_dir / "summary.json"


class ServiceState:
    def __init__(self, work_root: str) -> None:
        self.work_root = Path(work_root)
        self.work_root.mkdir(parents=True, exist_ok=True)
        self.jobs: dict[str, Job] = {}
        # Single-event-loop invariant: invoke() has no await between the
        # active_job() check and job registration, so no lock is needed;
        # adding an await there requires adding one.
        self.watchers: set[asyncio.Task] = set()  # strong refs (GC guard)

    def active_job(self) -> Job | None:
        for job in self.jobs.values():
            if job.state in ("pending", "running"):
                return job
        return None


def _runner_code(
    pipeline: str,
    args: dict,
    summary_path: str,
    work_dir: str = "",
    input_zip_url: str = "",
    output_zip_url: str = "",
    output_zip_multipart: dict | None = None,
) -> str:
    """Child-process program: optional presigned-zip ingest (reference
    nvcf_main.py handle_presigned_urls — credential-less I/O: inputs arrive
    as a GET-able zip, results leave as a PUT-able zip), run the pipeline,
    write summary.json, optional zip+upload of the output directory."""
    payload = json.dumps(
        {
            "pipeline": pipeline,
            "args": args,
            "summary": summary_path,
            "work_dir": work_dir,
            "input_zip_url": input_zip_url,
            "output_zip_url": output_zip_url,
            "output_zip_multipart": output_zip_multipart,
        }
    )
    return (
        "import json, sys\n"
        f"spec = json.loads({payload!r})\n"
        "args = spec['args']\n"
        "if spec['input_zip_url']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import download_and_extract\n"
        "    inp = spec['work_dir'] + '/input'\n"
        "    download_and_extract(spec['input_zip_url'], inp)\n"
        "    args['input_path'] = inp\n"
        "if (spec['output_zip_url'] or spec['output_zip_multipart']) and not args.get('output_path'):\n"
        "    args['output_path'] = spec['work_dir'] + '/output'\n"
        "from cosmos_curate_tpu.pipelines.video import split as split_mod\n"
        "from cosmos_curate_tpu.pipelines.video import dedup as dedup_mod\n"
        "from cosmos_curate_tpu.pipelines.video import shard as shard_mod\n"
        "if spec['pipeline'] == 'split':\n"
        "    s = split_mod.run_split(split_mod.SplitPipelineArgs(**args))\n"
        "elif spec['pipeline'] == 'dedup':\n"
        "    s = dedup_mod.run_dedup(dedup_mod.DedupPipelineArgs(**args))\n"
        "else:\n"
        "    s = shard_mod.run_shard(shard_mod.ShardPipelineArgs(**args))\n"
        "json.dump(s, open(spec['summary'], 'w'))\n"
        "if spec['output_zip_multipart']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import PresignedMultipart, zip_and_upload_directory\n"
        "    zip_and_upload_directory(args['output_path'], PresignedMultipart.from_dict(spec['output_zip_multipart']))\n"
        "elif spec['output_zip_url']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import zip_and_upload_directory\n"
        "    zip_and_upload_directory(args['output_path'], spec['output_zip_url'])\n"
    )


async def _watch_job(state: ServiceState, job: Job) -> None:
    loop = asyncio.get_running_loop()
    rc = await loop.run_in_executor(None, job.proc.wait)
    job.finished_s = time.time()
    if job.state == "terminated":
        return
    job.state = "done" if rc == 0 and job.summary_path.exists() else "failed"
    logger.info("job %s finished: %s (rc=%s)", job.job_id, job.state, rc)


def build_app(work_root: str = "/tmp/curate_service") -> web.Application:
    state = ServiceState(work_root)
    app = web.Application()
    app["state"] = state

    async def health(request: web.Request) -> web.Response:
        active = state.active_job()
        return web.json_response(
            {
                "status": "ok",
                "active_job": active.job_id if active else None,
                "num_jobs": len(state.jobs),
            }
        )

    async def invoke(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        pipeline = body.get("pipeline")
        args = body.get("args", {})
        if pipeline not in _PIPELINES:
            return web.json_response(
                {"error": f"pipeline must be one of {sorted(_PIPELINES)}"}, status=400
            )
        if not isinstance(args, dict):
            return web.json_response({"error": "args must be an object"}, status=400)
        if state.active_job() is not None:
            return web.json_response(
                {"error": "a pipeline is already running", "active_job": state.active_job().job_id},
                status=409,
            )
        input_zip_url = body.get("input_zip_url", "")
        output_zip_url = body.get("output_zip_url", "")
        # multi-GB outputs go through presigned multipart (per-part retry,
        # no single-PUT size limits, reference presigned_s3_zip.py:334)
        output_zip_multipart = body.get("output_zip_multipart")
        if not isinstance(input_zip_url, str) or not isinstance(output_zip_url, str):
            return web.json_response({"error": "zip urls must be strings"}, status=400)
        if output_zip_multipart is not None and (
            not isinstance(output_zip_multipart, dict)
            or not output_zip_multipart.get("part_urls")
            or not output_zip_multipart.get("complete_url")
        ):
            return web.json_response(
                {"error": "output_zip_multipart needs part_urls + complete_url"},
                status=400,
            )
        if (output_zip_url or output_zip_multipart) and "://" in str(args.get("output_path", "")):
            # zipping a remote output root would silently upload an empty
            # archive — the zip leaves from a local directory
            return web.json_response(
                {"error": "output_zip_url requires a local output_path (or none)"},
                status=400,
            )
        job_id = uuid.uuid4().hex[:12]
        work_dir = state.work_root / job_id
        work_dir.mkdir(parents=True)
        job = Job(job_id=job_id, pipeline=pipeline, args=args, work_dir=work_dir)
        log_f = open(job.log_path, "wb")
        try:
            job.proc = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    _runner_code(
                        pipeline,
                        args,
                        str(job.summary_path),
                        work_dir=str(work_dir),
                        input_zip_url=input_zip_url,
                        output_zip_url=output_zip_url,
                        output_zip_multipart=output_zip_multipart,
                    ),
                ],
                stdout=log_f,
                stderr=subprocess.STDOUT,
                cwd=str(Path(__file__).resolve().parents[2]),
            )
        except Exception as e:
            job.state = "failed"
            state.jobs[job_id] = job
            return web.json_response({"error": str(e), "job_id": job_id}, status=500)
        finally:
            log_f.close()  # child holds its own fd; parent must not leak one per job
        job.state = "running"
        state.jobs[job_id] = job
        task = asyncio.create_task(_watch_job(state, job))
        state.watchers.add(task)  # event loop holds only weak refs
        task.add_done_callback(state.watchers.discard)
        return web.json_response({"job_id": job_id, "state": job.state})

    def _get_job(request: web.Request) -> Job | None:
        return state.jobs.get(request.match_info["job_id"])

    async def progress(request: web.Request) -> web.Response:
        job = _get_job(request)
        if job is None:
            return web.json_response({"error": "unknown job"}, status=404)
        out = {
            "job_id": job.job_id,
            "pipeline": job.pipeline,
            "state": job.state,
            "elapsed_s": (job.finished_s or time.time()) - job.started_s,
        }
        if job.state == "done":
            out["summary"] = json.loads(job.summary_path.read_text())
        return web.json_response(out)

    async def logs(request: web.Request) -> web.Response:
        job = _get_job(request)
        if job is None:
            return web.json_response({"error": "unknown job"}, status=404)
        tail = int(request.query.get("tail", "200"))
        lines: list[str] = []
        if job.log_path.exists():
            lines = job.log_path.read_text(errors="replace").splitlines()[-tail:]
        return web.json_response({"job_id": job.job_id, "lines": lines})

    async def terminate(request: web.Request) -> web.Response:
        job = _get_job(request)
        if job is None:
            return web.json_response({"error": "unknown job"}, status=404)
        if job.proc is not None and job.proc.poll() is None:
            job.state = "terminated"
            job.proc.terminate()
        return web.json_response({"job_id": job.job_id, "state": job.state})

    async def models(request: web.Request) -> web.Response:
        """Weights-registry status (reference nvcf_model_manager equivalent:
        core/cf/nvcf_model_manager.py — which models a deployment has
        staged)."""
        from cosmos_curate_tpu.models import registry

        out = {}
        for mid in registry.registered_models():
            ckpt = registry.local_dir_for(mid) / "params.msgpack"
            out[mid] = {
                "staged": ckpt.exists(),
                "size_bytes": ckpt.stat().st_size if ckpt.exists() else 0,
            }
        return web.json_response({"weights_root": str(registry.weights_root()), "models": out})

    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/invoke", invoke)
    app.router.add_get("/v1/progress/{job_id}", progress)
    app.router.add_get("/v1/logs/{job_id}", logs)
    app.router.add_post("/v1/terminate/{job_id}", terminate)
    return app


def serve(host: str = "0.0.0.0", port: int = 8080, work_root: str = "/tmp/curate_service") -> None:
    web.run_app(build_app(work_root), host=host, port=port)
