"""Durable multi-tenant job service: crash-safe queue, admission, resume.

The reference gets its service shape from NVCF (cosmos_curate/core/cf/
nvcf_main.py — FastAPI wrapper, one-pipeline-at-a-time lock, in-memory job
dict), which forgets every queued and running job on restart. This service
is built for heavy multi-tenant traffic instead (aiohttp; fastapi is not
in this image):

  GET  /health                  liveness + READINESS (dispatcher running,
                                journal writable, queue depths per lane,
                                index generation when serving search)
  GET  /v1/jobs                 list jobs (?tenant=&state= filters)
  POST /v1/invoke               {"pipeline": ..., "args": {...},
                                 "tenant": "t", "priority": "interactive"}
  GET  /v1/progress/{job_id}    state, attempts, summary + run_report link
  GET  /v1/jobs/{job_id}/status the job child's latest LIVE snapshot
                                (per-stage queue/busy/in-flight batches)
                                + stall-detector verdicts
  GET  /v1/slo                  per-tenant queue-wait / run-duration /
                                success-rate vs configured targets
  GET  /v1/logs/{job_id}        bounded log tail (seeks, never slurps)
  POST /v1/terminate/{job_id}   kill the job's whole process group
  POST /v1/requeue/{job_id}     dead_lettered/failed/terminated → pending
  GET  /v1/models               staged-weights registry status
  POST /v1/search               similarity search over the corpus index
                                (service/search.py; needs --index-path;
                                 its own admission lane, sheds independently
                                 of the job queue)
  GET  /v1/search/stats         index-server generation/cache/lane stats

Durability: every state transition is journaled append-only under
``work_root`` (service/job_queue.py). A ``kill -9``'d service replays the
journal on boot, marks running jobs ``interrupted``, and re-enqueues them;
the re-run reuses the same args/output_path, so input-discovery resume
records skip already-completed videos. Admission (service/admission.py)
replaces the single-job lock with interactive/batch priority lanes,
per-tenant quotas, and load shedding (429 + Retry-After, never an
unbounded queue); a dispatcher runs up to N concurrent jobs gated by the
host's NodeBudget. Failures retry with full-jitter backoff up to
``max_attempts``, then land ``dead_lettered`` (requeueable). SIGTERM
drains gracefully: stop admitting, let running jobs finish within
``drain_s``, checkpoint the rest as ``interrupted`` for the next boot.

Jobs execute in their own *session* (``start_new_session=True``) so a
crashing pipeline never takes the service down and terminate kills the
entire worker tree, not just the direct child. Chaos sites
``service.job.crash`` (child start) and ``service.journal.write`` (journal
append) plug the whole thing into the fault-injection harness.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import re
import signal
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from aiohttp import web

from cosmos_curate_tpu.service.admission import (
    AdmissionController,
    QuotaConfig,
)
from cosmos_curate_tpu.service.job_queue import (
    JOB_STATES,
    LANES,
    TERMINAL_STATES,
    JobJournal,
    JobRecord,
    JournalWriteError,
    recover_records,
)
from cosmos_curate_tpu.service.slo import SloConfig, SloTracker
from cosmos_curate_tpu.storage.retry import backoff_s
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_PIPELINES = {"split", "dedup", "shard"}
_LOG_TAIL_MAX_BYTES = 1 << 20  # hard ceiling per /v1/logs read, multi-GB safe
_TENANT_RE = re.compile(r"[A-Za-z0-9._:-]{1,64}")


@dataclass(frozen=True)
class ServiceConfig:
    quota: QuotaConfig = field(default_factory=QuotaConfig)
    max_attempts: int = 3
    drain_s: float = 30.0  # SIGTERM: grace for running jobs to finish
    term_grace_s: float = 5.0  # terminate: SIGTERM → SIGKILL escalation
    retry_base_s: float = 0.5  # full-jitter backoff between attempts
    retry_cap_s: float = 30.0
    metrics_port: int | None = None
    # terminal-record GC: a long-lived service must not hold every job it
    # ever ran in memory/journal forever. Records in a terminal state are
    # evicted (journal tombstone + drop) after retain_terminal_s, and the
    # newest max_terminal_records are kept regardless of backlog size.
    retain_terminal_s: float = 86400.0
    max_terminal_records: int = 5000
    # per-tenant SLO targets (service/slo.py; `serve --slo-*` knobs).
    # Breaches increment service_slo_breaches_total{tenant,kind}, journal
    # against the job, and surface in GET /v1/slo.
    slo: SloConfig = field(default_factory=SloConfig)
    # live ops: how often the dispatcher re-reads a running job's live
    # snapshot to journal its anomaly verdicts + fold them into the
    # service's pipeline_anomalies_total (job children have no exporter)
    anomaly_scan_interval_s: float = 3.0


# ---------------------------------------------------------------------------
# job subprocess


def _runner_code(
    pipeline: str,
    args: dict,
    summary_path: str,
    work_dir: str = "",
    input_zip_url: str = "",
    output_zip_url: str = "",
    output_zip_multipart: dict | None = None,
) -> str:
    """Child-process program: optional presigned-zip ingest (reference
    nvcf_main.py handle_presigned_urls — credential-less I/O: inputs arrive
    as a GET-able zip, results leave as a PUT-able zip), run the pipeline,
    write summary.json, optional zip+upload of the output directory.

    The chaos preamble arms ``CURATE_CHAOS`` (handed through job_env) and
    fires ``service.job.crash`` — a crash-kind rule kills the job child
    before any work, exercising the retry/dead-letter path end to end."""
    payload = json.dumps(
        {
            "pipeline": pipeline,
            "args": args,
            "summary": summary_path,
            "work_dir": work_dir,
            "input_zip_url": input_zip_url,
            "output_zip_url": output_zip_url,
            "output_zip_multipart": output_zip_multipart,
        }
    )
    return (
        "import json, sys\n"
        "from cosmos_curate_tpu import chaos as _chaos\n"
        "_chaos.install_from_env()\n"
        "_chaos.fire('service.job.crash')\n"
        f"spec = json.loads({payload!r})\n"
        "args = spec['args']\n"
        "if spec['input_zip_url']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import download_and_extract\n"
        "    inp = spec['work_dir'] + '/input'\n"
        "    download_and_extract(spec['input_zip_url'], inp)\n"
        "    args['input_path'] = inp\n"
        "if (spec['output_zip_url'] or spec['output_zip_multipart']) and not args.get('output_path'):\n"
        "    args['output_path'] = spec['work_dir'] + '/output'\n"
        "from cosmos_curate_tpu.pipelines.video import split as split_mod\n"
        "from cosmos_curate_tpu.pipelines.video import dedup as dedup_mod\n"
        "from cosmos_curate_tpu.pipelines.video import shard as shard_mod\n"
        "if spec['pipeline'] == 'split':\n"
        "    s = split_mod.run_split(split_mod.SplitPipelineArgs(**args))\n"
        "elif spec['pipeline'] == 'dedup':\n"
        "    s = dedup_mod.run_dedup(dedup_mod.DedupPipelineArgs(**args))\n"
        "else:\n"
        "    s = shard_mod.run_shard(shard_mod.ShardPipelineArgs(**args))\n"
        "json.dump(s, open(spec['summary'], 'w'))\n"
        "if spec['output_zip_multipart']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import PresignedMultipart, zip_and_upload_directory\n"
        "    zip_and_upload_directory(args['output_path'], PresignedMultipart.from_dict(spec['output_zip_multipart']))\n"
        "elif spec['output_zip_url']:\n"
        "    from cosmos_curate_tpu.storage.zip_transport import zip_and_upload_directory\n"
        "    zip_and_upload_directory(args['output_path'], spec['output_zip_url'])\n"
    )


def _default_runner_cmd(record: JobRecord, work_dir: Path) -> list[str]:
    return [
        sys.executable,
        "-c",
        _runner_code(
            record.pipeline,
            record.args,
            str(work_dir / "summary.json"),
            work_dir=str(work_dir),
            input_zip_url=record.input_zip_url,
            output_zip_url=record.output_zip_url,
            output_zip_multipart=record.output_zip_multipart,
        ),
    ]


def job_env(record: JobRecord | None = None) -> dict[str, str]:
    """The job subprocess environment: a full copy of the ambient env —
    which by construction carries the cross-process contracts
    ``CURATE_CHAOS`` (armed fault plans fire inside job children) and
    ``CURATE_DLQ_DIR`` (the job's engine dead-letters where the operator
    configured); tests/service pin that guarantee down in the child — plus
    two additions the ambient env cannot provide:

    - ``CURATE_TRACING`` / ``CURATE_TRACEPARENT``: when the service itself
      is tracing, its *current span* (not just an inherited env var)
      becomes the job's process parent, so one trace spans
      submit → job → pipeline workers
    - ``CURATE_WORKER_ID=job-<id>-a<attempt>``: chaos rules target a
      specific attempt (``worker_re="-a1$"`` faults only the first try),
      and crash recovery uses it to identify orphaned job processes
    """
    env = dict(os.environ)
    from cosmos_curate_tpu.observability.tracing import (
        TRACEPARENT_ENV,
        format_traceparent,
        tracing_enabled,
    )

    if tracing_enabled() or os.environ.get("CURATE_TRACING") == "1":
        env["CURATE_TRACING"] = "1"
        tp = format_traceparent() or os.environ.get(TRACEPARENT_ENV, "")
        if tp:
            env[TRACEPARENT_ENV] = tp
    if record is not None:
        env["CURATE_WORKER_ID"] = f"job-{record.job_id}-a{record.attempts}"
    return env


def tail_lines(path: Path, n: int, *, max_bytes: int = _LOG_TAIL_MAX_BYTES) -> list[str]:
    """Last ``n`` lines of ``path`` without reading the whole file: seek to
    the end and walk backwards in blocks until enough newlines (or the
    ``max_bytes`` cap) — a multi-GB job log costs one bounded read."""
    if not path.exists() or n <= 0:
        return []
    block = 64 * 1024
    chunks: list[bytes] = []
    newlines = 0
    read = 0
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        pos = f.tell()
        while pos > 0 and newlines <= n and read < max_bytes:
            step = min(block, pos, max_bytes - read)
            pos -= step
            f.seek(pos)
            chunk = f.read(step)
            chunks.append(chunk)
            newlines += chunk.count(b"\n")
            read += step
    text = b"".join(reversed(chunks)).decode("utf-8", errors="replace")
    return text.splitlines()[-n:]


# ---------------------------------------------------------------------------
# service state


class ServiceState:
    def __init__(
        self,
        work_root: str,
        config: ServiceConfig,
        *,
        runner_cmd: Callable[[JobRecord, Path], list[str]] | None = None,
    ) -> None:
        self.work_root = Path(work_root)
        self.work_root.mkdir(parents=True, exist_ok=True)
        self.config = config
        self.journal = JobJournal(self.work_root / "journal.ndjson")
        self.admission = AdmissionController(config.quota)
        self.runner_cmd = runner_cmd or _default_runner_cmd
        self.jobs: dict[str, JobRecord] = {}
        self.procs: dict[str, subprocess.Popen] = {}
        self.draining = False
        self.stopping = False  # dispatcher exit flag (cooperative, not cancel)
        self.watchers: set[asyncio.Task] = set()  # strong refs (GC guard)
        self.wake: asyncio.Event | None = None  # created on the app's loop
        self.slo = SloTracker(config.slo)
        # readiness: flips False on a journal append failure, True on the
        # next success — /health's journal_writable field
        self.journal_ok = True
        self.dispatcher_running = False
        # live-ops anomaly relay: job_id -> anomaly_count already journaled
        # (job children detect; the service journals + exports for them)
        self._anomaly_seen: dict[str, int] = {}
        self._anomaly_scan_at = 0.0
        # journaling executor: ONE thread so appends stay ordered without a
        # lock, and the fsync never runs on the event loop (an fsync on the
        # loop stalls every in-flight request — interactive-lane latency
        # paying for batch-job journaling). Sync callers (_recover at boot,
        # tests) still call record_transition directly.
        self._journal_exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="journal"
        )
        from cosmos_curate_tpu.engine.metrics import get_metrics

        self.metrics = get_metrics(config.metrics_port)
        self._recover()

    # ---- durability ----------------------------------------------------

    def _recover(self) -> None:
        """Boot-time journal replay: re-enqueue pending/interrupted jobs,
        compact the journal back to one line per job."""
        records, requeue_ids = recover_records(self.journal)
        self.jobs = records
        now = time.time()
        for job_id in requeue_ids:
            rec = self.jobs[job_id]
            was = rec.state
            rec.state = "pending"
            rec.enqueued_s = now
            self.admission.requeue(rec)
            self.record_transition(rec, f"recovered-{was}")
            logger.info("job %s recovered from journal (%s → pending)", job_id, was)
        self.journal.compact(self.jobs)
        self._export_states()

    def record_transition(self, rec: JobRecord, event: str, *, required: bool = False) -> None:
        """Journal + metrics for one transition. ``required=True`` (the
        submit ack) propagates a journal failure to the caller; otherwise
        durability degrades to in-memory with a loud log — resume records
        make the resulting re-run idempotent."""
        try:
            self.journal.append(rec, event)
            self.journal_ok = True
        except JournalWriteError:
            self.journal_ok = False
            if required:
                raise
            logger.exception(
                "journal append failed for job %s (%s); state held in memory only",
                rec.job_id, event,
            )
        self.metrics.observe_service_transition(rec.tenant, rec.state)
        if rec.state in TERMINAL_STATES and event not in ("evicted",):
            # SLO accounting: run duration + success window on every
            # terminal entry (a requeued job that dies again is a new
            # outcome — correct: the tenant experienced both)
            duration = (
                (rec.finished_s - rec.started_s)
                if rec.finished_s and rec.started_s
                else None
            )
            self._note_slo_breaches(
                rec, self.slo.observe_terminal(rec.tenant, rec.state, duration)
            )
        self._export_states()

    async def on_journal_thread(self, fn: Callable, *args, **kwargs):
        """Run a journaling (fsync-bearing) callable on the single-thread
        journal executor. Appends stay ordered (one thread) and the event
        loop never blocks on the disk."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._journal_exec, functools.partial(fn, *args, **kwargs)
        )

    async def record_transition_async(
        self, rec: JobRecord, event: str, *, required: bool = False
    ) -> None:
        """:meth:`record_transition` off the event loop — what every
        coroutine must use (the blocking-in-async lint rule enforces it)."""
        await self.on_journal_thread(
            self.record_transition, rec, event, required=required
        )

    def _export_states(self) -> None:
        counts = {s: 0 for s in JOB_STATES}
        # list(): this runs on the journal thread too, concurrent with
        # loop-side inserts/evictions of self.jobs
        for rec in list(self.jobs.values()):
            counts[rec.state] = counts.get(rec.state, 0) + 1
        self.metrics.set_service_states(counts)
        for lane in LANES:
            self.metrics.set_service_queue_depth(lane, self.admission.lane_depth(lane))

    def _note_slo_breaches(self, rec: JobRecord, kinds: list[str]) -> None:
        """Metrics + a journal receipt per breached SLO kind. Raw journal
        append (never record_transition — the record's state did not
        change, and a breach must not re-fire the terminal SLO hook);
        replay ignores unknown events, so durability semantics hold."""
        for kind in kinds:
            self.metrics.observe_slo_breach(rec.tenant, kind)
            logger.warning(
                "SLO breach (%s) for tenant %s on job %s",
                kind, rec.tenant, rec.job_id,
            )
            try:
                self.journal.append(rec, f"slo-breach:{kind}")
            except JournalWriteError:
                self.journal_ok = False

    # ---- live ops ------------------------------------------------------

    def output_root(self, rec: JobRecord) -> Path:
        """The job's pipeline output root (where run_report.json and the
        live status snapshot land)."""
        return Path(
            str(rec.args.get("output_path") or self.work_dir(rec.job_id) / "output")
        )

    def job_live_status(self, rec: JobRecord) -> dict | None:
        """The job child's latest live snapshot (None before the first
        publish / for pipelines that don't publish)."""
        from cosmos_curate_tpu.observability.live_status import read_status

        return read_status(str(self.output_root(rec)))

    def scan_job_anomalies(self, now: float | None = None) -> int:
        """Dispatcher-tick relay: read each running job's live snapshot and
        journal (+ export) anomaly verdicts the job child detected — the
        child has no journal and no metrics exporter, the service has both.
        Rate-limited; returns how many NEW anomalies were relayed."""
        now = time.time() if now is None else now
        if now - self._anomaly_scan_at < self.config.anomaly_scan_interval_s:
            return 0
        self._anomaly_scan_at = now
        relayed = 0
        for rec in self.running_records():
            snap = self.job_live_status(rec)
            if not snap:
                continue
            total = int(snap.get("anomaly_count") or 0)
            seen = self._anomaly_seen.get(rec.job_id, 0)
            if total <= seen:
                continue
            # the snapshot carries a bounded tail of recent events; relay
            # the newest (total - seen), or the whole tail if more
            # happened than the tail kept
            tail = [ev for ev in (snap.get("anomalies") or []) if isinstance(ev, dict)]
            for ev in tail[-min(total - seen, len(tail)) :] if tail else ():
                self.metrics.observe_anomaly(
                    str(ev.get("stage") or "_run"), str(ev.get("kind") or "unknown")
                )
                try:
                    self.journal.append(rec, f"anomaly:{ev.get('kind')}")
                except JournalWriteError:
                    self.journal_ok = False
                relayed += 1
            self._anomaly_seen[rec.job_id] = total
        # forget jobs that left the running set (bounded growth)
        running = {r.job_id for r in self.running_records()}
        for job_id in [j for j in list(self._anomaly_seen) if j not in running]:
            del self._anomaly_seen[job_id]
        return relayed

    # ---- paths ---------------------------------------------------------

    def work_dir(self, job_id: str) -> Path:
        return self.work_root / "jobs" / job_id

    def log_path(self, job_id: str) -> Path:
        return self.work_dir(job_id) / "job.log"

    def summary_path(self, job_id: str) -> Path:
        return self.work_dir(job_id) / "summary.json"

    def report_path(self, rec: JobRecord) -> Path:
        """The job's flight-recorder receipt (observability/flight_recorder.py
        writes ``<output>/report/run_report.json`` at finalize)."""
        out = str(rec.args.get("output_path") or self.work_dir(rec.job_id) / "output")
        return Path(out) / "report" / "run_report.json"

    # ---- queries -------------------------------------------------------

    def running_records(self) -> list[JobRecord]:
        # list(): called from both the loop and the journal thread
        return [r for r in list(self.jobs.values()) if r.state == "running"]

    def gc_terminal(self) -> None:
        """Evict old terminal records (dispatcher tick). Each eviction is a
        journal tombstone, so a restart doesn't resurrect them; a journal
        outage just defers the eviction to a later tick."""
        from cosmos_curate_tpu.service.job_queue import TERMINAL_STATES

        now = time.time()
        terminal = sorted(
            (
                r for r in list(self.jobs.values())
                if r.state in TERMINAL_STATES and r.finished_s
            ),
            key=lambda r: r.finished_s,
        )
        expired = [
            r for r in terminal
            if now - r.finished_s > self.config.retain_terminal_s
        ]
        overflow = len(terminal) - len(expired) - self.config.max_terminal_records
        if overflow > 0:
            keep = [r for r in terminal if now - r.finished_s <= self.config.retain_terminal_s]
            expired.extend(keep[:overflow])  # oldest first
        for rec in expired:
            try:
                self.journal.append(rec, "evicted")
            except JournalWriteError:
                continue  # keep the record; retry next tick
            del self.jobs[rec.job_id]
        if expired:
            self._export_states()

    def state_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for rec in list(self.jobs.values()):
            counts[rec.state] = counts.get(rec.state, 0) + 1
        return counts

    def kick(self) -> None:
        if self.wake is not None:
            self.wake.set()


# ---------------------------------------------------------------------------
# dispatch + supervision


def _spawn_proc(state: ServiceState, rec: JobRecord, work_dir: Path) -> subprocess.Popen:
    """Blocking half of a launch (log open + fork/exec): runs on an
    executor thread, never on the event loop."""
    work_dir.mkdir(parents=True, exist_ok=True)
    log_f = open(state.log_path(rec.job_id), "ab")
    try:
        return subprocess.Popen(
            state.runner_cmd(rec, work_dir),
            stdout=log_f,
            stderr=subprocess.STDOUT,
            cwd=str(Path(__file__).resolve().parents[2]),
            env=job_env(rec),
            start_new_session=True,  # session leader: killpg reaps the tree
        )
    finally:
        log_f.close()  # child holds its own fd; parent must not leak one per job


async def _launch(state: ServiceState, rec: JobRecord) -> None:
    """Spawn one attempt of ``rec`` in its own session. A spawn failure is
    terminal ``failed`` (the command never started — retrying a bad spec
    only burns attempts). The fork/exec and the journal appends run on
    executor threads; every ``await`` is an interleave point, so the
    terminated-while-launching race is re-checked after the spawn."""
    rec.attempts += 1
    work_dir = state.work_dir(rec.job_id)
    wait_s = max(0.0, time.time() - rec.enqueued_s)
    loop = asyncio.get_running_loop()
    try:
        proc = await loop.run_in_executor(
            None, functools.partial(_spawn_proc, state, rec, work_dir)
        )
    except Exception as e:
        rec.state = "failed"
        rec.error = f"spawn failed: {e}"
        rec.finished_s = time.time()
        await state.record_transition_async(rec, "spawn-failed")
        logger.exception("job %s spawn failed", rec.job_id)
        return
    if rec.state == "terminated":
        # terminate() landed while the fork/exec was in flight: honor the
        # operator's verdict — kill the fresh group; the watcher reaps it
        # without resurrecting (terminate already journaled the state)
        state.procs[rec.job_id] = proc
        _killpg(proc.pid, signal.SIGTERM)
        task = asyncio.create_task(_watch_job(state, rec, proc))
        state.watchers.add(task)
        task.add_done_callback(state.watchers.discard)
        return
    rec.state = "running"
    rec.pid = proc.pid
    if rec.started_s is None:
        rec.started_s = time.time()
    state.procs[rec.job_id] = proc
    await state.record_transition_async(rec, "running")
    state.metrics.observe_service_dispatch(rec.priority, wait_s)
    await state.on_journal_thread(
        state._note_slo_breaches, rec, state.slo.observe_dispatch(rec.tenant, wait_s)
    )
    # fresh attempt = fresh detector: its anomaly_count restarts at 0, so
    # a stale high-water mark from a prior attempt would suppress relay
    state._anomaly_seen.pop(rec.job_id, None)
    task = asyncio.create_task(_watch_job(state, rec, proc))
    state.watchers.add(task)  # event loop holds only weak refs
    task.add_done_callback(state.watchers.discard)
    logger.info(
        "job %s dispatched (tenant=%s lane=%s attempt %d/%d pid=%d, waited %.2fs)",
        rec.job_id, rec.tenant, rec.priority, rec.attempts, rec.max_attempts,
        proc.pid, wait_s,
    )


async def _watch_job(state: ServiceState, rec: JobRecord, proc: subprocess.Popen) -> None:
    loop = asyncio.get_running_loop()
    rc = await loop.run_in_executor(None, proc.wait)
    state.procs.pop(rec.job_id, None)
    rec.pid = None
    if rec.state in ("terminated", "interrupted"):
        # terminate() / drain checkpoint already journaled the state; the
        # exit just confirms the kill landed
        rec.finished_s = rec.finished_s or time.time()
        state.kick()
        return
    if rc == 0 and state.summary_path(rec.job_id).exists():
        rec.state = "done"
        rec.finished_s = time.time()
        rec.error = ""
        await state.record_transition_async(rec, "done")
        logger.info("job %s done (attempt %d)", rec.job_id, rec.attempts)
        state.kick()
        return
    tail = tail_lines(state.log_path(rec.job_id), 5)
    rec.error = f"exit code {rc}" + (f": {tail[-1][:500]}" if tail else "")
    if rec.attempts >= rec.max_attempts:
        rec.state = "dead_lettered"
        rec.finished_s = time.time()
        await state.record_transition_async(rec, "dead-lettered")
        logger.error(
            "job %s dead-lettered after %d attempts (%s)",
            rec.job_id, rec.attempts, rec.error,
        )
        state.kick()
        return
    # transient failure: full-jitter backoff, then back into the lane. The
    # record flips to pending BEFORE the sleep — a backing-off job must not
    # hold a dispatch slot (or its tenant's running cap) while no process
    # exists, and a crash during the sleep replays it as plain pending.
    delay = backoff_s(
        rec.attempts - 1, base=state.config.retry_base_s, cap=state.config.retry_cap_s
    )
    logger.warning(
        "job %s attempt %d/%d failed (%s); retrying in %.2fs",
        rec.job_id, rec.attempts, rec.max_attempts, rec.error, delay,
    )
    rec.state = "pending"
    await state.record_transition_async(rec, "retry")
    state.kick()  # freed capacity is usable during the backoff
    if not state.draining:
        await asyncio.sleep(delay)
    if rec.state == "terminated":
        # the operator terminated the job during the backoff sleep; honor
        # the kill, don't resurrect
        rec.finished_s = rec.finished_s or time.time()
        state.kick()
        return
    if state.draining:
        # journaled pending: the next boot's replay re-enqueues it
        state.kick()
        return
    # enqueued_s stamps AFTER the backoff: queue-wait must measure time
    # spent waiting for capacity, not the deliberate retry delay
    rec.enqueued_s = time.time()
    state.admission.requeue(rec)
    state.kick()


async def _dispatch_loop(app: web.Application) -> None:
    """The scheduler: drain admission lanes into subprocesses whenever
    capacity frees up. Woken by submit/finish/retry; 0.5 s tick as a
    backstop."""
    state: ServiceState = app["state"]
    state.wake = asyncio.Event()
    # exits via state.stopping, NOT task cancellation: py3.10's wait_for can
    # swallow a CancelledError that races its timeout expiry (bpo-42130),
    # which left a cancelled dispatcher looping forever and shutdown hung
    state.dispatcher_running = True
    try:
        while not state.stopping:
            state.wake.clear()
            if not state.draining:
                while True:
                    rec = state.admission.pop_next(state.running_records())
                    if rec is None:
                        break
                    if rec.job_id not in state.jobs:
                        # submit ack (journal append) still in flight on the
                        # executor — invoke() inserts into state.jobs only
                        # after the fsync lands. Not dispatchable yet; put it
                        # back and let the next tick retry.
                        state.admission.requeue(rec)
                        break
                    await _launch(state, rec)
                await state.on_journal_thread(state.gc_terminal)
                state._export_states()
            try:
                # live-ops relay rides the dispatcher tick: journal + export
                # anomaly verdicts running job children published (reads
                # snapshots + appends, so it runs on the journal thread)
                await state.on_journal_thread(state.scan_job_anomalies)
            except Exception:
                logger.exception("anomaly scan failed (dispatcher unaffected)")
            try:
                await asyncio.wait_for(state.wake.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass
    finally:
        state.dispatcher_running = False


def _killpg(pid: int, sig: int) -> None:
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError):
        pass


async def _escalate_kill(proc: subprocess.Popen, grace_s: float) -> None:
    """SIGTERM was sent to the job's process group; if the group leader is
    still alive after ``grace_s``, SIGKILL the whole group. Worker
    subprocesses of a terminated job must not outlive it."""
    loop = asyncio.get_running_loop()
    try:
        await asyncio.wait_for(loop.run_in_executor(None, proc.wait), grace_s)
    except asyncio.TimeoutError:
        _killpg(proc.pid, signal.SIGKILL)


async def drain_app(app: web.Application, drain_s: float | None = None) -> None:
    """Graceful SIGTERM drain: stop admitting (invoke → 503), let running
    jobs finish within ``drain_s``, checkpoint survivors as ``interrupted``
    (journaled → next boot resumes them), leave queued jobs journaled
    ``pending``. After this returns every job is terminal or journaled for
    the next boot — nothing is silently forgotten."""
    state: ServiceState = app["state"]
    state.draining = True
    deadline = time.monotonic() + (state.config.drain_s if drain_s is None else drain_s)
    while state.procs and time.monotonic() < deadline:
        await asyncio.sleep(0.1)
    survivors = list(state.procs.items())
    for job_id, proc in survivors:
        rec = state.jobs[job_id]
        if rec.state == "running":
            # a proc in a non-running state is a terminated job mid-kill:
            # kill it with the rest but keep the operator's verdict — the
            # next boot must NOT resurrect it as interrupted
            rec.state = "interrupted"
            rec.pid = None
            await state.record_transition_async(rec, "drain-checkpoint")
            logger.info("drain: job %s checkpointed as interrupted", job_id)
        _killpg(proc.pid, signal.SIGTERM)
    if survivors:
        grace = min(2.0, state.config.term_grace_s)
        loop = asyncio.get_running_loop()
        for _, proc in survivors:
            try:
                await asyncio.wait_for(loop.run_in_executor(None, proc.wait), grace)
            except asyncio.TimeoutError:
                _killpg(proc.pid, signal.SIGKILL)
    state.kick()


# ---------------------------------------------------------------------------
# HTTP surface


def build_app(
    work_root: str = "/tmp/curate_service",
    config: ServiceConfig | None = None,
    *,
    runner_cmd: Callable[[JobRecord, Path], list[str]] | None = None,
    search_config=None,
) -> web.Application:
    cfg = config or ServiceConfig()
    state = ServiceState(work_root, cfg, runner_cmd=runner_cmd)
    app = web.Application()
    app["state"] = state
    search_state = None
    if search_config is not None and getattr(search_config, "index_path", ""):
        # retrieval rides next to the job API, but with its OWN admission
        # lane (service/search.py): searches shed on their own quota,
        # independent of the job queue
        from cosmos_curate_tpu.service.search import SearchState, register_search_routes

        search_state = SearchState(search_config)
        app["search"] = search_state
        register_search_routes(app, search_state)

    async def health(request: web.Request) -> web.Response:
        """Liveness AND readiness in one payload: k8s-style probes read
        ``ready`` (dispatcher running + journal writable + not draining),
        `top` reads the same fields — one source for both."""
        running = state.running_records()
        # cheap journal probe between appends: the parent dir must remain
        # writable or the next submit will 503 — surface it here first
        journal_writable = state.journal_ok and os.access(
            state.journal.path.parent, os.W_OK
        )
        dispatcher_running = state.dispatcher_running
        out = {
            "status": "draining" if state.draining else "ok",
            "ready": bool(
                dispatcher_running and journal_writable and not state.draining
            ),
            "dispatcher_running": dispatcher_running,
            "journal_writable": journal_writable,
            "active_job": running[0].job_id if running else None,
            "running_jobs": [r.job_id for r in running],
            "num_jobs": len(state.jobs),
            "states": state.state_counts(),
            "queued": {lane: state.admission.lane_depth(lane) for lane in LANES},
            "max_concurrent": state.admission.effective_max_running(),
            "slo_enabled": state.config.slo.enabled,
        }
        if search_state is not None:
            out["search"] = search_state.stats()
            # index-server generation, hoisted for readiness probes that
            # gate on "serving search at generation >= N"
            gen = out["search"].get("generation")
            if gen is not None:
                out["index_generation"] = gen
        return web.json_response(out)

    async def list_jobs(request: web.Request) -> web.Response:
        tenant = request.query.get("tenant", "")
        want_state = request.query.get("state", "")
        out = []
        for rec in state.jobs.values():
            if tenant and rec.tenant != tenant:
                continue
            if want_state and rec.state != want_state:
                continue
            out.append(
                {
                    "job_id": rec.job_id,
                    "pipeline": rec.pipeline,
                    "tenant": rec.tenant,
                    "priority": rec.priority,
                    "state": rec.state,
                    "attempts": rec.attempts,
                    "pid": rec.pid,
                }
            )
        return web.json_response({"jobs": out})

    async def invoke(request: web.Request) -> web.Response:
        if state.draining:
            return web.json_response(
                {"error": "service is draining"},
                status=503,
                headers={"Retry-After": "30"},
            )
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        if not isinstance(body, dict):
            # valid JSON but not an object ([1,2], "split", 3): .get below
            # would 500, not 400
            return web.json_response({"error": "body must be a JSON object"}, status=400)
        pipeline = body.get("pipeline")
        args = body.get("args", {})
        if pipeline not in _PIPELINES:
            return web.json_response(
                {"error": f"pipeline must be one of {sorted(_PIPELINES)}"}, status=400
            )
        if not isinstance(args, dict):
            return web.json_response({"error": "args must be an object"}, status=400)
        tenant = body.get("tenant", "default")
        priority = body.get("priority", "batch")
        if not isinstance(tenant, str) or not _TENANT_RE.fullmatch(tenant):
            # bounded charset+length: the tenant string becomes a journal
            # field, a work-dir-adjacent id, and a prometheus label
            return web.json_response(
                {"error": "tenant must match [A-Za-z0-9._:-]{1,64}"}, status=400
            )
        if priority not in LANES:
            return web.json_response(
                {"error": f"priority must be one of {list(LANES)}"}, status=400
            )
        try:
            max_attempts = int(body.get("max_attempts", cfg.max_attempts))
        except (TypeError, ValueError):
            return web.json_response({"error": "max_attempts must be an int"}, status=400)
        if max_attempts < 1:
            return web.json_response({"error": "max_attempts must be >= 1"}, status=400)
        input_zip_url = body.get("input_zip_url", "")
        output_zip_url = body.get("output_zip_url", "")
        # multi-GB outputs go through presigned multipart (per-part retry,
        # no single-PUT size limits, reference presigned_s3_zip.py:334)
        output_zip_multipart = body.get("output_zip_multipart")
        if not isinstance(input_zip_url, str) or not isinstance(output_zip_url, str):
            return web.json_response({"error": "zip urls must be strings"}, status=400)
        if output_zip_multipart is not None and (
            not isinstance(output_zip_multipart, dict)
            or not output_zip_multipart.get("part_urls")
            or not output_zip_multipart.get("complete_url")
        ):
            return web.json_response(
                {"error": "output_zip_multipart needs part_urls + complete_url"},
                status=400,
            )
        if (output_zip_url or output_zip_multipart) and "://" in str(args.get("output_path", "")):
            # zipping a remote output root would silently upload an empty
            # archive — the zip leaves from a local directory
            return web.json_response(
                {"error": "output_zip_url requires a local output_path (or none)"},
                status=400,
            )
        rec = JobRecord.new(
            pipeline,
            args,
            tenant=tenant,
            priority=priority,
            max_attempts=max_attempts,
            input_zip_url=input_zip_url,
            output_zip_url=output_zip_url,
            output_zip_multipart=output_zip_multipart,
        )
        decision = state.admission.admit(rec)
        if not decision.admitted:
            if not decision.retry_after_s:  # malformed, not over-capacity
                return web.json_response({"error": decision.reason}, status=400)
            # never-admitted tenants (tenant_limit, or queue_full before
            # first admission) must not mint new metric label series
            shed_label = tenant if state.admission.is_known_tenant(tenant) else "_other"
            state.metrics.observe_service_shed(shed_label, decision.reason)
            logger.warning(
                "shed %s job from tenant %s: %s (retry after %.1fs)",
                priority, tenant, decision.reason, decision.retry_after_s,
            )
            return web.json_response(
                {
                    "error": "over quota, retry later",
                    "reason": decision.reason,
                    "retry_after_s": decision.retry_after_s,
                },
                status=429,
                headers={"Retry-After": str(int(decision.retry_after_s) or 1)},
            )
        try:
            # durability gate: the ack implies the journal has the job. The
            # fsync happens on the journal thread; the dispatcher skips
            # admitted-but-not-yet-acked records (not in state.jobs) so the
            # await below cannot race a launch.
            await state.record_transition_async(rec, "submit", required=True)
        except JournalWriteError as e:
            state.admission.remove(rec.job_id)
            logger.error("refusing job: %s", e)
            return web.json_response(
                {"error": f"journal unavailable: {e}"}, status=503
            )
        state.jobs[rec.job_id] = rec
        state.kick()
        return web.json_response(
            {
                "job_id": rec.job_id,
                "state": rec.state,
                "tenant": rec.tenant,
                "priority": rec.priority,
            }
        )

    def _get_job(request: web.Request) -> JobRecord | None:
        return state.jobs.get(request.match_info["job_id"])

    async def progress(request: web.Request) -> web.Response:
        rec = _get_job(request)
        if rec is None:
            return web.json_response({"error": "unknown job"}, status=404)
        out = {
            "job_id": rec.job_id,
            "pipeline": rec.pipeline,
            "tenant": rec.tenant,
            "priority": rec.priority,
            "state": rec.state,
            "attempts": rec.attempts,
            "max_attempts": rec.max_attempts,
            "elapsed_s": (rec.finished_s or time.time()) - rec.submitted_s,
        }
        if rec.error:
            out["error"] = rec.error
        if rec.state == "done":
            out["summary"] = json.loads(state.summary_path(rec.job_id).read_text())
        report = state.report_path(rec)
        if report.exists():
            # the tenant-facing receipt: trace ids, critical path, per-stage
            # times (render with `cosmos-curate-tpu report`)
            out["report"] = str(report)
        return web.json_response(out)

    async def job_status(request: web.Request) -> web.Response:
        """Live in-flight introspection for one job: the child's latest
        atomically-swapped snapshot (per-stage queue/busy/in-flight data)
        plus the stall detector's verdicts — /v1/progress tells you the
        job's lifecycle state, THIS tells you whether it is actually
        moving."""
        from cosmos_curate_tpu.observability.live_status import snapshot_age_s

        rec = _get_job(request)
        if rec is None:
            return web.json_response({"error": "unknown job"}, status=404)
        snap = state.job_live_status(rec)
        out = {
            "job_id": rec.job_id,
            "state": rec.state,
            "tenant": rec.tenant,
            "attempts": rec.attempts,
            "live": snap is not None,
            "output_path": str(state.output_root(rec)),
        }
        if snap is None:
            out["detail"] = (
                "no live snapshot yet (job not started, pipeline predates "
                "live status, or output root is remote)"
            )
        else:
            out["snapshot"] = snap
            out["snapshot_age_s"] = round(snapshot_age_s(snap), 3)
            out["anomalies"] = snap.get("anomalies") or []
            out["anomaly_count"] = int(snap.get("anomaly_count") or 0)
            out["stale"] = bool(
                rec.state == "running"
                and snap.get("state") == "running"
                and out["snapshot_age_s"] > 30.0
            )
        return web.json_response(out)

    async def slo(request: web.Request) -> web.Response:
        """Per-tenant SLO standing: observed queue-wait / run-duration /
        success-rate against the configured targets, with breach counts
        (the counter view is service_slo_breaches_total{tenant,kind})."""
        report = state.slo.report()
        # live context: what each tenant has queued/running right now
        occupancy: dict[str, dict] = {}
        for rec in state.jobs.values():
            occ = occupancy.setdefault(rec.tenant, {"queued": 0, "running": 0})
            if rec.state == "pending":
                occ["queued"] += 1
            elif rec.state == "running":
                occ["running"] += 1
        report["occupancy"] = occupancy
        return web.json_response(report)

    async def logs(request: web.Request) -> web.Response:
        rec = _get_job(request)
        if rec is None:
            return web.json_response({"error": "unknown job"}, status=404)
        try:
            tail = int(request.query.get("tail", "200"))
        except ValueError:
            return web.json_response({"error": "tail must be an int"}, status=400)
        lines = tail_lines(state.log_path(rec.job_id), tail)
        return web.json_response({"job_id": rec.job_id, "lines": lines})

    async def terminate(request: web.Request) -> web.Response:
        rec = _get_job(request)
        if rec is None:
            return web.json_response({"error": "unknown job"}, status=404)
        if rec.state == "pending":
            state.admission.remove(rec.job_id)
            rec.state = "terminated"
            rec.finished_s = time.time()
            await state.record_transition_async(rec, "terminated-queued")
        elif rec.state == "running":
            rec.state = "terminated"
            rec.finished_s = time.time()
            await state.record_transition_async(rec, "terminated")
            proc = state.procs.get(rec.job_id)
            if proc is not None and proc.poll() is None:
                # the whole process group: pipeline worker subprocesses must
                # not outlive a terminated job. SIGTERM first, SIGKILL after
                # the grace window.
                _killpg(proc.pid, signal.SIGTERM)
                task = asyncio.create_task(
                    _escalate_kill(proc, cfg.term_grace_s)
                )
                state.watchers.add(task)
                task.add_done_callback(state.watchers.discard)
        return web.json_response({"job_id": rec.job_id, "state": rec.state})

    async def requeue(request: web.Request) -> web.Response:
        rec = _get_job(request)
        if rec is None:
            return web.json_response({"error": "unknown job"}, status=404)
        if state.draining:
            return web.json_response({"error": "service is draining"}, status=503)
        if rec.state not in ("dead_lettered", "failed", "terminated"):
            return web.json_response(
                {"error": f"cannot requeue a {rec.state} job"}, status=409
            )
        if rec.job_id in state.procs:
            # a terminated job whose SIGTERM→SIGKILL escalation is still in
            # flight: re-admitting now would run two copies against one
            # work_dir and let the old exit corrupt the new attempt's state
            return web.json_response(
                {"error": "job process is still exiting; retry shortly"},
                status=409,
            )
        snapshot = (rec.state, rec.attempts, rec.error, rec.finished_s, rec.enqueued_s)
        rec.attempts = 0
        rec.error = ""
        rec.state = "pending"
        rec.finished_s = None
        rec.enqueued_s = time.time()
        decision = state.admission.admit(rec)
        if not decision.admitted:
            # shed: the record must be exactly as it was before the request
            rec.state, rec.attempts, rec.error, rec.finished_s, rec.enqueued_s = snapshot
            state.metrics.observe_service_shed(rec.tenant, decision.reason)
            return web.json_response(
                {"error": "over quota, retry later", "reason": decision.reason},
                status=429,
                headers={"Retry-After": str(int(decision.retry_after_s) or 1)},
            )
        await state.record_transition_async(rec, "requeued")
        state.kick()
        return web.json_response({"job_id": rec.job_id, "state": rec.state})

    async def models(request: web.Request) -> web.Response:
        """Weights-registry status (reference nvcf_model_manager equivalent:
        core/cf/nvcf_model_manager.py — which models a deployment has
        staged)."""
        from cosmos_curate_tpu.models import registry

        out = {}
        for mid in registry.registered_models():
            ckpt = registry.local_dir_for(mid) / "params.msgpack"
            out[mid] = {
                "staged": ckpt.exists(),
                "size_bytes": ckpt.stat().st_size if ckpt.exists() else 0,
            }
        return web.json_response({"weights_root": str(registry.weights_root()), "models": out})

    async def _start_dispatcher(app: web.Application) -> None:
        app["dispatcher"] = asyncio.create_task(_dispatch_loop(app))

    async def _stop_dispatcher(app: web.Application) -> None:
        state.stopping = True
        state.kick()
        task = app.get("dispatcher")
        if task is not None:
            try:
                await asyncio.wait_for(task, 5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                task.cancel()  # backstop; the flag should have sufficed
        for watcher in list(state.watchers):
            watcher.cancel()
        # after the dispatcher and watchers stop, nothing schedules journal
        # work; drain the queued appends before the process exits
        state._journal_exec.shutdown(wait=True)

    app.on_startup.append(_start_dispatcher)
    app.on_cleanup.append(_stop_dispatcher)

    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/v1/jobs", list_jobs)
    app.router.add_post("/v1/invoke", invoke)
    app.router.add_get("/v1/progress/{job_id}", progress)
    app.router.add_get("/v1/jobs/{job_id}/status", job_status)
    app.router.add_get("/v1/slo", slo)
    app.router.add_get("/v1/logs/{job_id}", logs)
    app.router.add_post("/v1/terminate/{job_id}", terminate)
    app.router.add_post("/v1/requeue/{job_id}", requeue)
    return app


def serve(
    host: str = "0.0.0.0",
    port: int = 8080,
    work_root: str = "/tmp/curate_service",
    config: ServiceConfig | None = None,
    search_config=None,
) -> None:
    """Run the service until SIGTERM/SIGINT, then drain gracefully."""
    cfg = config or ServiceConfig()

    async def _main() -> None:
        app = build_app(work_root=work_root, config=cfg, search_config=search_config)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        logger.info("job service on %s:%d (work_root=%s)", host, port, work_root)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        logger.info("signal received: draining (up to %.0fs)", cfg.drain_s)
        await drain_app(app)
        await runner.cleanup()

    asyncio.run(_main())
