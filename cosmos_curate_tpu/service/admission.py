"""Admission control: priority lanes, per-tenant quotas, load shedding.

Replaces the reference's one-pipeline-at-a-time lock (the NVCF wrapper's
middleware, reproduced in the old ``service/app.py``) with the admission
shape heavy multi-tenant traffic needs:

- **Priority lanes.** Two lanes, ``interactive`` and ``batch``; the
  dispatcher always drains ``interactive`` first. Within a lane each
  tenant has its own FIFO and tenants are served round-robin, so one
  tenant's thousand-job backfill cannot starve another's single job.
- **Quotas.** Per-tenant queued and running caps plus a global queued cap.
  Over-quota submissions are *shed* — a ``429`` with ``Retry-After`` —
  instead of accepted into an unbounded queue (or the old ``409``-forever).
- **Capacity.** The dispatcher runs up to ``max_concurrent_jobs`` jobs,
  additionally clamped by the host's :class:`~cosmos_curate_tpu.engine.autoscaler.NodeBudget`
  (CPU/memory) under a per-job cost estimate — the same accounting the
  cross-host planner uses, so a 2-core box never dispatches 8 pipelines.

Pure data structure + policy: no IO, no clocks beyond the records' own
timestamps, trivially unit-testable. The service (``service/app.py``)
owns journaling and subprocesses.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass
from typing import Iterable

from cosmos_curate_tpu.engine.autoscaler import NodeBudget
from cosmos_curate_tpu.service.job_queue import LANES, JobRecord
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class QuotaConfig:
    """Admission knobs. Defaults are sized for a small box; the serve CLI
    exposes all of them."""

    max_concurrent_jobs: int = 2
    max_running_per_tenant: int = 2
    max_queued_per_tenant: int = 8
    max_queued_total: int = 64
    # dispatcher-side resource estimate per job (a pipeline subprocess
    # spawns its own worker pool, so one job ≈ one core minimum)
    cpus_per_job: float = 1.0
    memory_gb_per_job: float = 0.0
    retry_after_s: float = 5.0  # base Retry-After hint; scaled by backlog
    # cap on DISTINCT tenants ever admitted: the tenant string is
    # client-chosen and becomes per-tenant queue structures and a
    # prometheus label — without a cap, randomized tenant names are an
    # unbounded-memory (and quota-bypass) vector
    max_tenants: int = 256


@dataclass(frozen=True)
class Decision:
    """``admit`` outcome: accepted into a lane, or shed with the reason
    that becomes the 429 body + ``service_shed_total{reason}`` label."""

    admitted: bool
    reason: str = ""
    retry_after_s: float = 0.0


def host_budget() -> NodeBudget:
    """This host as a :class:`NodeBudget` (the planner's accounting unit).
    Memory probe is best-effort — 0.0 disables the memory clamp, matching
    the planner's "participates only where both sides declare" rule."""
    mem_gb = 0.0
    try:
        import psutil

        mem_gb = psutil.virtual_memory().total / 2**30
    except Exception:  # psutil absent or /proc unreadable: CPU clamp only
        pass
    return NodeBudget(node_id="", cpus=float(os.cpu_count() or 1), memory_gb=mem_gb)


class AdmissionController:
    """Lane/tenant queues + the quota and capacity policy.

    Not thread-safe by itself: the service drives it from one event loop.
    """

    def __init__(self, cfg: QuotaConfig, budget: NodeBudget | None = None) -> None:
        self.cfg = cfg
        self.budget = budget or host_budget()
        # lane -> tenant -> FIFO of queued records
        self._lanes: dict[str, dict[str, deque[JobRecord]]] = {
            lane: {} for lane in LANES
        }
        # lane -> tenant round-robin order (rotated on every pop)
        self._rr: dict[str, deque[str]] = {lane: deque() for lane in LANES}
        self._known_tenants: set[str] = set()  # bounded by cfg.max_tenants

    # ---- introspection -------------------------------------------------

    def is_known_tenant(self, tenant: str) -> bool:
        """True once a tenant has been admitted at least once. Metric
        labels for unknown tenants must use a sentinel — shedding a
        never-admitted tenant must not mint the label series the
        ``max_tenants`` cap exists to bound."""
        return tenant in self._known_tenants

    def queued_total(self) -> int:
        return sum(
            len(q) for lane in self._lanes.values() for q in lane.values()
        )

    def queued_for(self, tenant: str) -> int:
        return sum(len(lane.get(tenant, ())) for lane in self._lanes.values())

    def lane_depth(self, lane: str) -> int:
        return sum(len(q) for q in self._lanes[lane].values())

    def queued_records(self) -> list[JobRecord]:
        out: list[JobRecord] = []
        for lane in LANES:
            for q in self._lanes[lane].values():
                out.extend(q)
        return out

    def effective_max_running(self) -> int:
        """The dispatcher cap after the host budget clamp: never more jobs
        than the host has CPU (and, when both sides declare, memory) for."""
        cap = self.cfg.max_concurrent_jobs
        if self.cfg.cpus_per_job > 0:
            cap = min(cap, int(self.budget.cpus // self.cfg.cpus_per_job))
        if self.cfg.memory_gb_per_job > 0 and self.budget.memory_gb > 0:
            cap = min(
                cap, int(self.budget.memory_gb // self.cfg.memory_gb_per_job)
            )
        return max(1, cap)  # a 0.5-core container still runs one job

    def _retry_after(self, extra_backlog: int = 0) -> float:
        """Retry-After hint: base, scaled by how many dispatch slots the
        backlog represents. Coarse on purpose — it only needs to spread a
        herd of retries, not predict completion."""
        slots = self.effective_max_running()
        backlog = self.queued_total() + extra_backlog
        return round(self.cfg.retry_after_s * (1.0 + backlog / max(1, slots)), 1)

    # ---- admission -----------------------------------------------------

    def admit(self, record: JobRecord) -> Decision:
        """Quota check + enqueue. Sheds (never queues) when over quota."""
        if record.priority not in LANES:
            return Decision(False, reason=f"unknown lane {record.priority!r}")
        if (
            record.tenant not in self._known_tenants
            and len(self._known_tenants) >= self.cfg.max_tenants
        ):
            return Decision(
                False, reason="tenant_limit", retry_after_s=self._retry_after()
            )
        if self.queued_total() >= self.cfg.max_queued_total:
            return Decision(
                False, reason="queue_full", retry_after_s=self._retry_after()
            )
        if self.queued_for(record.tenant) >= self.cfg.max_queued_per_tenant:
            return Decision(
                False, reason="tenant_queue_full", retry_after_s=self._retry_after()
            )
        self._enqueue(record)
        return Decision(True)

    def _enqueue(self, record: JobRecord) -> None:
        self._known_tenants.add(record.tenant)
        lane = self._lanes[record.priority]
        if record.tenant not in lane:
            lane[record.tenant] = deque()
            self._rr[record.priority].append(record.tenant)
        lane[record.tenant].append(record)

    def requeue(self, record: JobRecord) -> None:
        """Unconditional re-enqueue: retries and crash-recovered jobs were
        already admitted once and must not be shed on the way back in."""
        self._enqueue(record)

    def remove(self, job_id: str) -> JobRecord | None:
        """Drop a queued record (terminate-before-start)."""
        for lane in LANES:
            for tenant, q in self._lanes[lane].items():
                for rec in q:
                    if rec.job_id == job_id:
                        q.remove(rec)
                        return rec
        return None

    # ---- dispatch ------------------------------------------------------

    def pop_next(self, running: Iterable[JobRecord]) -> JobRecord | None:
        """The next record to dispatch, or None when at capacity / empty.

        Interactive lane strictly first; within a lane, round-robin across
        tenants (skipping tenants at their running cap), FIFO within a
        tenant."""
        running = list(running)
        if len(running) >= self.effective_max_running():
            return None
        running_by_tenant: dict[str, int] = {}
        for rec in running:
            running_by_tenant[rec.tenant] = running_by_tenant.get(rec.tenant, 0) + 1
        for lane in LANES:  # ("interactive", "batch") — priority order
            order = self._rr[lane]
            for _ in range(len(order)):
                tenant = order[0]
                order.rotate(-1)
                q = self._lanes[lane].get(tenant)
                if not q:
                    continue
                if (
                    running_by_tenant.get(tenant, 0)
                    >= self.cfg.max_running_per_tenant
                ):
                    continue
                return q.popleft()
        return None
