"""Durable job records: an append-only journal that survives service crashes.

The reference service (PAPER.md L6, NVCF wrapper) keeps its one job in
memory behind a single-pipeline lock — a restart forgets everything. This
module is the service's source of truth instead: every job record (spec,
tenant, priority, state transition, attempt count) is journaled to an
append-only NDJSON log under ``work_root``, so a service that comes back
after ``kill -9`` replays the journal, marks jobs that were ``running`` at
crash time as ``interrupted``, and re-enqueues them. Re-invocation reuses
the job's original ``work_dir`` and args, so the split pipeline's
input-discovery resume records (``pipelines/video/input_discovery.py``)
skip every video the dead run already completed.

Journal layout (``<work_root>/journal.ndjson``)::

    {"schema_version": 2, "ts": ..., "event": "submit", "record": {...full JobRecord...}}
    {"schema_version": 2, "ts": ..., "event": "running", "record": {...}}
    ...

Each line is a full snapshot of the record at that transition: replay is
"last line per job_id wins", which tolerates a torn final line (a crash
mid-append) by discarding it. On startup the replayed state is compacted
back to one line per job so the journal stays O(jobs), not O(transitions).

Every line is stamped with the ``job-journal`` schema version
(utils/schema_stamp.py): replay carries version-N−1 lines forward through
the registered migration shims — a service restarted onto a new build
mid-deploy replays the old build's journal with zero lost or duplicated
jobs — and refuses (line-by-line, loudly) anything newer than this build
publishes. The line shape itself is a ``lint --schema`` contract surface:
drifting it without a bump (and, for breaking drift, a shim) fails CI.

Lifecycle::

    pending ──▶ running ──▶ done
                  │  │
                  │  ├──▶ failed        (spawn error: never started)
                  │  ├──▶ terminated    (operator kill)
                  │  ├──▶ interrupted   (service died / drain checkpoint)
                  │  │        └──▶ pending   (replayed + re-enqueued)
                  │  └──▶ pending       (non-zero exit, attempts left)
                  │            └──▶ dead_lettered (attempts exhausted)
                  └───────────────────────▶ (requeue: dead_lettered ▶ pending)

Terminal states are ``done | failed | dead_lettered | terminated``;
``interrupted`` and ``pending`` only survive until the next dispatch.

The chaos site ``service.journal.write`` fires at the top of every append,
so the fault-injection harness (docs/FAULT_TOLERANCE.md) can prove a
journal outage degrades to a refused submission, not a lost job.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field, asdict
from pathlib import Path

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.utils import schema_stamp
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LANES = ("interactive", "batch")

# every state a record can journal as; used to zero per-state gauges
JOB_STATES = (
    "pending",
    "running",
    "interrupted",
    "done",
    "failed",
    "dead_lettered",
    "terminated",
)
TERMINAL_STATES = frozenset({"done", "failed", "dead_lettered", "terminated"})


@dataclass
class JobRecord:
    """One job, as journaled. ``args`` is the pipeline-args dict the child
    process receives; re-running the same record is what makes resume work
    (same output_path → input discovery skips completed videos)."""

    job_id: str
    pipeline: str
    args: dict
    tenant: str = "default"
    priority: str = "batch"  # one of LANES
    state: str = "pending"
    attempts: int = 0  # dispatches so far (1-based after first spawn)
    max_attempts: int = 3
    submitted_s: float = field(default_factory=time.time)
    enqueued_s: float = field(default_factory=time.time)  # reset on requeue
    started_s: float | None = None
    finished_s: float | None = None
    pid: int | None = None  # session-leader pid while running (ops + crash cleanup)
    error: str = ""  # tail of the last failure reason
    # presigned-zip transport (reference handle_presigned_urls)
    input_zip_url: str = ""
    output_zip_url: str = ""
    output_zip_multipart: dict | None = None

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: dict) -> "JobRecord":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in doc.items() if k in known})

    @classmethod
    def new(cls, pipeline: str, args: dict, **kw) -> "JobRecord":
        return cls(job_id=uuid.uuid4().hex[:12], pipeline=pipeline, args=args, **kw)


class JournalWriteError(RuntimeError):
    """An append could not be made durable. Submissions must be refused
    (503) rather than accepted into a queue that would forget them."""


class JobJournal:
    """Append-only NDJSON journal with last-line-wins replay.

    Appends flush+fsync before returning: once a submission is acked, a
    ``kill -9`` one instruction later still replays it. The fsync runs on
    the CALLER's thread — the service keeps it off its event loop by
    routing every coroutine-side append through a single-thread journal
    executor (``ServiceState.record_transition_async``; the
    ``blocking-in-async`` lint rule enforces this), which also serializes
    appends without a lock. Failures raise
    :class:`JournalWriteError` — the caller decides whether that refuses a
    submission (yes) or degrades a mid-run transition to in-memory-only
    (also yes, with a loud log: losing one transition downgrades a resumed
    job to a re-run, which resume records make idempotent anyway).
    """

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def append(self, record: JobRecord, event: str) -> None:
        line = json.dumps(
            schema_stamp.stamp(
                {"ts": time.time(), "event": event, "record": record.to_dict()},
                "job-journal",
            )
        )
        try:
            # InjectedFault is a ConnectionError: an armed
            # service.journal.write rule surfaces as JournalWriteError, the
            # same shape as a real disk failure
            chaos.fire(chaos.SITE_SERVICE_JOURNAL_WRITE)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        except (OSError, ConnectionError) as e:
            raise JournalWriteError(f"journal append failed: {e}") from e

    def replay(self) -> dict[str, JobRecord]:
        """Last snapshot per job_id, submission-ordered. A torn final line
        (crash mid-append) is discarded; any other unparseable line is
        skipped with a warning rather than wedging startup."""
        records: dict[str, JobRecord] = {}
        if not self.path.exists():
            return records
        try:
            lines = self.path.read_text(encoding="utf-8", errors="replace").splitlines()
        except OSError as e:
            logger.error("journal unreadable (%s); starting empty", e)
            return records
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
                # version-N−1 lines (including historical unstamped v1) flow
                # through the shim chain; newer-than-this-build lines pass
                # as-is (strict=False) because from_dict drops unknown
                # fields — best-effort beats wedging a rollback's startup.
                # A missing shim raises SchemaVersionError (a ValueError),
                # landing in the corrupt-line path below: skipped loudly.
                doc = schema_stamp.upgrade(doc, "job-journal", strict=False)
                rec = JobRecord.from_dict(doc["record"])
            except (ValueError, KeyError, TypeError) as e:
                if i == len(lines) - 1:
                    logger.warning("discarding torn journal tail line: %s", e)
                else:
                    logger.warning("skipping corrupt journal line %d: %s", i + 1, e)
                continue
            if doc.get("event") == "evicted":
                # GC tombstone (app.ServiceState.gc_terminal): the record
                # was terminal and aged out — drop it from replay too
                records.pop(rec.job_id, None)
                continue
            records[rec.job_id] = rec
        return records

    def compact(self, records: dict[str, JobRecord]) -> None:
        """Atomically rewrite the journal to one line per job. Called at
        startup after replay; a failure leaves the old (longer but valid)
        journal in place."""
        tmp = self.path.with_suffix(".ndjson.tmp")
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in records.values():
                    f.write(
                        json.dumps(
                            schema_stamp.stamp(
                                {
                                    "ts": time.time(),
                                    "event": "compact",
                                    "record": rec.to_dict(),
                                },
                                "job-journal",
                            )
                        )
                        + "\n"
                    )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            logger.warning("journal compaction failed (keeping long journal): %s", e)
            tmp.unlink(missing_ok=True)


def _pgid_is_own_session(pid: int) -> bool:
    """True when ``pid`` leads its own process group — the shape every job
    child has (``start_new_session=True``). Guards crash-recovery cleanup
    against killing an unrelated process that reused the pid."""
    try:
        return os.getpgid(pid) == pid
    except (OSError, PermissionError):
        return False


def _is_job_process(pid: int, job_id: str) -> bool:
    """Identity check before the orphan SIGKILL: group-leadership alone is
    not enough under pid reuse (any daemon is its own session leader after
    a host reboot). Every job child is stamped
    ``CURATE_WORKER_ID=job-<job_id>-a<n>`` (service/app.py job_env), so on
    Linux ``/proc/<pid>/environ`` identifies it exactly; when /proc is
    unreadable (non-Linux, permissions) fall back to the session check."""
    marker = f"CURATE_WORKER_ID=job-{job_id}-a".encode()
    try:
        env_blob = Path(f"/proc/{pid}/environ").read_bytes()
    except OSError:
        return _pgid_is_own_session(pid)
    return marker in env_blob


def recover_records(
    journal: JobJournal, *, kill_orphans: bool = True
) -> tuple[dict[str, JobRecord], list[str]]:
    """Replay + crash recovery: returns ``(records, requeue_ids)``.

    Jobs whose last journaled state was ``running`` were alive when the
    previous service died — they are marked ``interrupted`` and queued for
    re-enqueue. A job process that *outlived* the dead service would keep
    writing while the resumed copy runs, so its process group is killed
    first (only when the pid still leads its own session — see
    :func:`_pgid_is_own_session`). ``pending``/``interrupted`` records
    re-enqueue as-is; terminal records are kept for listing only.
    """
    import signal

    records = journal.replay()
    requeue: list[str] = []
    for rec in records.values():
        # ANY record still carrying a pid had a live process when the
        # service died — including a job journaled `terminated` where the
        # crash beat the killpg. Reap it before re-running anything, or
        # the orphan keeps writing next to the resumed copy.
        if kill_orphans and rec.pid and _is_job_process(rec.pid, rec.job_id):
            try:
                os.killpg(rec.pid, signal.SIGKILL)
                logger.warning(
                    "killed orphaned job process group %d (job %s) from dead service",
                    rec.pid, rec.job_id,
                )
            except (OSError, PermissionError):
                pass
        rec.pid = None
        if rec.state == "running":
            rec.state = "interrupted"
        if rec.state in ("pending", "interrupted"):
            requeue.append(rec.job_id)
    return records, requeue
