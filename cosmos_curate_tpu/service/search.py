"""``POST /v1/search`` — the service surface of the index-server read path.

Similarity search over the corpus index (dedup/index_server.py) exposed
next to the job API (service/app.py), with its OWN admission lane: search
is an interactive workload with millisecond budgets, so it sheds under
its own quota (``max_inflight`` + ``max_waiting``, 429 + Retry-After)
completely independently of the job queue — a batch-job backlog can never
starve search, and a search herd can never eat job dispatch capacity.

Request body (exactly one of ``embedding`` / ``clip_uuid`` / ``text``):

    {"embedding": [...float, index dim], "top_k": 8, "nprobe": 0}
    {"clip_uuid": "<indexed clip id>", ...}
    {"text": "a red car at night", ...}        # CLIP text tower, provenance-gated

Response:

    {"mode": "clip|uuid|text", "generation": N,
     "results": [{"clip_uuid": ..., "score": ...}, ...],
     "latency_ms": 3.1}

``generation`` is the manifest generation that answered — queries running
concurrently with background compaction return generation-consistent
results (one snapshot per micro-batch, never a half-published manifest).
Errors: 400 malformed, 403 provenance-refused text search, 404 unknown
clip_uuid, 429 lane over capacity, 503 no index configured.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass

from aiohttp import web

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class SearchConfig:
    """Knobs for the in-service index server (see `serve` / `index serve`
    CLI). ``index_path`` empty = search disabled."""

    index_path: str = ""
    # admission lane: requests actively being served + waiting in the
    # micro-batch queue; beyond the sum, shed with 429
    max_inflight: int = 8
    max_waiting: int = 32
    retry_after_s: float = 1.0
    top_k_max: int = 64
    text_model: str = "clip-text-b-tpu"
    cache_bytes: int | None = None
    warmup: bool = True
    batch_window_s: float = 0.002
    max_batch: int = 64
    adopt_interval_s: float = 1.0
    # background compaction cadence; 0 disables the thread (use
    # `index compact` out of band instead)
    compact_interval_s: float = 0.0
    metrics_name: str = "index_server"


class SearchLane:
    """Search's own admission: a bounded in-flight + waiting counter,
    deliberately NOT the job AdmissionController — searches shed on their
    own quota so the two workloads degrade independently. Async-safe
    (driven from one event loop, like the job admission)."""

    def __init__(self, cfg: SearchConfig) -> None:
        self.cfg = cfg
        self.active = 0
        self.shed_total = 0

    def try_acquire(self) -> bool:
        if self.active >= self.cfg.max_inflight + self.cfg.max_waiting:
            self.shed_total += 1
            return False
        self.active += 1
        return True

    def release(self) -> None:
        self.active = max(0, self.active - 1)

    def retry_after_s(self) -> float:
        backlog = max(0, self.active - self.cfg.max_inflight)
        return round(
            self.cfg.retry_after_s * (1.0 + backlog / max(1, self.cfg.max_inflight)), 1
        )


class SearchState:
    """Owns the IndexServer + optional CompactionThread for one app."""

    def __init__(self, cfg: SearchConfig) -> None:
        self.cfg = cfg
        self.lane = SearchLane(cfg)
        self.server = None
        self.compactor = None

    def start(self) -> None:
        from cosmos_curate_tpu.dedup.index_server import IndexServer

        self.server = IndexServer(
            self.cfg.index_path,
            cache_bytes=self.cfg.cache_bytes,
            warmup=self.cfg.warmup,
            text_model=self.cfg.text_model,
            metrics_name=self.cfg.metrics_name,
            batch_window_s=self.cfg.batch_window_s,
            max_batch=self.cfg.max_batch,
            adopt_interval_s=self.cfg.adopt_interval_s,
            gc_drained=self.cfg.compact_interval_s > 0,
        )
        if self.cfg.compact_interval_s > 0:
            from cosmos_curate_tpu.dedup.compaction import CompactionThread

            self.compactor = CompactionThread(
                self.cfg.index_path,
                interval_s=self.cfg.compact_interval_s,
                metrics_name=f"{self.cfg.metrics_name}/compaction",
            )
            self.compactor.start()

    def stop(self) -> None:
        if self.compactor is not None:
            self.compactor.stop()
            self.compactor = None
        if self.server is not None:
            self.server.close()
            self.server = None

    def stats(self) -> dict:
        out = {
            "enabled": bool(self.server),
            "inflight": self.lane.active,
            "shed_total": self.lane.shed_total,
        }
        if self.server is not None:
            out.update(self.server.stats())
        if self.compactor is not None:
            out["compaction_passes"] = self.compactor.passes
        return out


def _shed_metric(name: str, reason: str) -> None:
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics
        from cosmos_curate_tpu.observability.stage_timer import record_search

        get_metrics().observe_search_shed(name, reason)
        record_search(name, shed=1)
    except Exception:
        logger.debug("search shed metric failed", exc_info=True)


def register_search_routes(app: web.Application, search: SearchState) -> None:
    """Mount ``POST /v1/search`` (+ ``GET /v1/search/stats``) on ``app``.
    The IndexServer starts on app startup (after the event loop exists)
    and closes on cleanup."""

    async def _start(app: web.Application) -> None:
        try:
            search.start()
            logger.info(
                "search serving index at %s (generation %d, %d vectors)",
                search.cfg.index_path,
                search.server.generation,
                search.server.stats()["num_vectors"],
            )
        except Exception:
            # the job service must still come up when the index is absent
            # or unreadable (missing dir, corrupt manifest pointer, ...);
            # /v1/search answers 503 until an index exists and the service
            # restarts — a read-path artifact must never take down the
            # job queue
            logger.exception("search disabled (index at %s unusable)", search.cfg.index_path)
            search.stop()

    async def _stop(app: web.Application) -> None:
        search.stop()

    async def handle_search(request: web.Request) -> web.Response:
        if search.server is None:
            return web.json_response(
                {"error": "no corpus index configured (serve --index-path)"},
                status=503,
            )
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be a JSON object"}, status=400)
        embedding = body.get("embedding")
        clip_uuid = body.get("clip_uuid")
        text = body.get("text")
        given = [x is not None for x in (embedding, clip_uuid, text)]
        if sum(given) != 1:
            return web.json_response(
                {"error": "exactly one of embedding/clip_uuid/text"}, status=400
            )
        if embedding is not None and (
            not isinstance(embedding, list)
            or not embedding
            or not all(isinstance(v, (int, float)) for v in embedding)
        ):
            return web.json_response(
                {"error": "embedding must be a non-empty list of numbers"}, status=400
            )
        if clip_uuid is not None and not isinstance(clip_uuid, str):
            return web.json_response({"error": "clip_uuid must be a string"}, status=400)
        if text is not None and (not isinstance(text, str) or not text.strip()):
            return web.json_response(
                {"error": "text must be a non-empty string"}, status=400
            )
        try:
            top_k = int(body.get("top_k", 8))
            nprobe = int(body.get("nprobe", 0))
        except (TypeError, ValueError):
            return web.json_response({"error": "top_k/nprobe must be ints"}, status=400)
        if not 1 <= top_k <= search.cfg.top_k_max:
            return web.json_response(
                {"error": f"top_k must be in [1, {search.cfg.top_k_max}]"}, status=400
            )
        if not 0 <= nprobe <= 4096:
            # 0 = the index default; a negative or absurd fan-out must not
            # fault the whole corpus through the warm cache
            return web.json_response(
                {"error": "nprobe must be in [0, 4096]"}, status=400
            )
        if not search.lane.try_acquire():
            retry = search.lane.retry_after_s()
            _shed_metric(search.cfg.metrics_name, "lane_full")
            return web.json_response(
                {"error": "search over capacity, retry later", "retry_after_s": retry},
                status=429,
                headers={"Retry-After": str(int(retry) or 1)},
            )
        t0 = time.monotonic()
        try:
            import numpy as np

            from cosmos_curate_tpu.dedup.index_server import ProvenanceError

            loop = asyncio.get_running_loop()
            kwargs = {"top_k": top_k, "nprobe": nprobe or None}
            if embedding is not None:
                mode = "clip"
                vec = np.asarray(embedding, np.float32)
                call = lambda: search.server.search(vec, **kwargs)  # noqa: E731
            elif clip_uuid is not None:
                mode = "uuid"
                call = lambda: search.server.search(clip_uuid=clip_uuid, **kwargs)  # noqa: E731
            else:
                mode = "text"
                call = lambda: search.server.search(text=text, **kwargs)  # noqa: E731
            try:
                results, generation = await loop.run_in_executor(None, call)
            except ProvenanceError as e:
                return web.json_response({"error": str(e)}, status=403)
            except KeyError as e:
                return web.json_response({"error": str(e.args[0] if e.args else e)}, status=404)
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            return web.json_response(
                {
                    "mode": mode,
                    "generation": generation,
                    "results": [
                        {"clip_uuid": cid, "score": score} for cid, score in results[0]
                    ],
                    "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
                }
            )
        finally:
            search.lane.release()

    async def handle_stats(request: web.Request) -> web.Response:
        return web.json_response(search.stats())

    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)
    app.router.add_post("/v1/search", handle_search)
    app.router.add_get("/v1/search/stats", handle_stats)


def build_search_app(cfg: SearchConfig) -> web.Application:
    """A standalone search-only app (the ``index serve`` CLI): /health +
    /v1/search, no job queue, no dispatcher."""
    app = web.Application()
    search = SearchState(cfg)
    app["search"] = search

    async def health(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "ok" if search.server is not None else "no-index",
                "search": search.stats(),
            }
        )

    app.router.add_get("/health", health)
    register_search_routes(app, search)
    return app


def serve_index(
    host: str = "0.0.0.0",
    port: int = 8081,
    cfg: SearchConfig | None = None,
) -> None:
    """Run the standalone index server until SIGTERM/SIGINT."""
    import signal

    config = cfg or SearchConfig()

    async def _main() -> None:
        app = build_search_app(config)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        logger.info(
            "index server on %s:%d (index=%s)", host, port, config.index_path
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await runner.cleanup()

    asyncio.run(_main())
