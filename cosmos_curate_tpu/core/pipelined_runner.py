"""PipelinedRunner: stage-overlapped execution on one host.

The reference gets its throughput from stage-parallel actor pools that keep
every stage of the pipeline running concurrently (Cosmos-Xenna's streaming
engine, reference ARCHITECTURE.md:20-110); our ``SequentialRunner`` runs the
stages in lockstep, so the CPU decode/transcode stages sit idle while the
device embeds and vice versa. ``PipelinedRunner`` is the single-host middle
ground: every stage runs in its own worker-thread pool, connected by bounded
inter-stage queues with backpressure, so decode of video N+1 overlaps the
embedding of video N — without the worker-process spawn cost that makes the
streaming engine a poor fit for 1-2 core boxes.

Semantics shared with the other runners (tests/core/test_pipelined_runner.py
locks output-set equivalence against ``SequentialRunner``):

- lifecycle per stage: ``setup_on_node`` → ``setup`` exactly ONCE per stage
  (worker threads share the stage instance — the process-pool runners give
  each worker a private copy instead), ``process_data`` per batch,
  ``destroy`` exactly once when the stage drains or the run aborts;
- ``StageSpec.num_run_attempts`` retries a failing batch in place; an
  exhausted batch aborts the run (``raise_on_error=True``) or is dropped
  through the durable dead-letter queue (engine/dead_letter.py), exactly
  like the streaming engine's permanent-drop path;
- dynamic chunking: a stage may emit more or fewer tasks than it received;
- chaos sites ``worker.batch.crash``/``worker.batch.hang`` fire per batch
  attempt (chaos/harness.py), so fault-injection suites cover this runner.

Placement rules:

- **device stages** — any stage whose model pins dispatch
  (``ModelInterface.pin_to_single_worker``) or that requests TPU resources —
  get exactly ONE worker thread, so the jit/bucket state inside
  ``models/device_pipeline.py`` stays single-threaded;
- **CPU stages fan out** only when they declare ``thread_safe = True``
  (concurrent ``process_data`` on disjoint batches is safe). Pool sizes
  come from the same water-filling planner the streaming engine uses
  (engine/autoscaler.py), re-planned every ``replan_interval_s`` as
  throughput samples arrive — the balanced-throughput problem is identical,
  only the worker unit (thread vs process) differs.

Known limits, both documented engine caveats for in-process workers:
``batch_timeout_s`` is not enforced (threads cannot be killed), and chaos
``worker_re`` filters match the process-wide ``CURATE_WORKER_ID``, not
individual worker threads.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field, replace

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.core.pipeline import PipelineSpec
from cosmos_curate_tpu.core.runner import RunnerInterface
from cosmos_curate_tpu.core.stage import NodeInfo, StageSpec, WorkerMetadata
from cosmos_curate_tpu.core.tasks import PipelineTask

# engine reuse is a hard dependency of this runner (the water-filling
# planner, the gauges, the durable DLQ); importing eagerly lets
# default_runner() degrade to SequentialRunner when the engine is absent
from cosmos_curate_tpu.engine.autoscaler import (
    Budget,
    StageScaleState,
    discover_tpu_chips,
    plan_allocation,
)
from cosmos_curate_tpu.engine.dead_letter import DeadLetterQueue, record_exhausted_batch
from cosmos_curate_tpu.engine.metrics import get_metrics
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _TaskQueue:
    """Bounded task queue between adjacent stages.

    ``put_many`` blocks while the queue is at capacity (backpressure on the
    producer); ``get_batch`` assembles up to ``max_size`` tasks, lingering
    briefly for a fuller batch while the producer is still alive (fuller
    batches keep device bucket shapes warm). ``close()`` marks the producer
    done: once closed AND empty, ``get_batch`` returns None and the stage's
    workers exit.
    """

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, capacity)
        self._buf: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cond:
            return len(self._buf)

    def set_capacity(self, capacity: int) -> None:
        with self._cond:
            self.capacity = max(1, capacity)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def drained(self) -> bool:
        """Producer done and nothing left to hand out."""
        with self._cond:
            return self._closed and not self._buf

    def put_many(self, tasks: list, should_stop) -> None:
        for t in tasks:
            with self._cond:
                while len(self._buf) >= self.capacity:
                    if should_stop():
                        return
                    self._cond.wait(0.05)
                self._buf.append(t)
                self._cond.notify_all()

    def get_batch(self, max_size: int, should_stop, linger_s: float) -> list | None:
        with self._cond:
            while True:
                if should_stop():
                    return None
                if self._buf:
                    break
                if self._closed:
                    return None
                self._cond.wait(0.05)
            batch = [self._buf.popleft()]
            deadline = time.monotonic() + linger_s
            while len(batch) < max_size:
                if self._buf:
                    batch.append(self._buf.popleft())
                    continue
                if self._closed or should_stop():
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.05))
            self._cond.notify_all()  # wake producers blocked on capacity
            return batch


@dataclass
class _Worker:
    meta: WorkerMetadata
    stop: threading.Event = field(default_factory=threading.Event)
    thread: threading.Thread | None = None


class _StageRuntime:
    """One stage's queue, thread pool, and shared bookkeeping."""

    def __init__(self, idx: int, spec: StageSpec, in_q: _TaskQueue, emit) -> None:
        self.idx = idx
        self.spec = spec
        self.stage = spec.stage
        self.in_q = in_q
        self.emit = emit  # callable(list[PipelineTask]) -> None
        self.workers: list[_Worker] = []
        self.lock = threading.Lock()
        # setup/destroy run exactly once per stage; the first worker thread
        # in claims setup, the rest block on the event
        self.setup_state = "pending"  # pending | running | ok | failed
        self.setup_done = threading.Event()
        self.destroyed = False
        self.finalized = False
        self.next_worker_idx = 0
        self.next_batch_id = 0
        # in-flight batches for the live ops plane: batch_id -> {started,
        # worker, attempt} (guarded by self.lock). A hung process_data is
        # visible here the whole time it hangs — the stuck_batch signal.
        self.inflight: dict[int, dict] = {}
        # accounting (guarded by self.lock)
        self.busy_s = 0.0
        self.samples: deque = deque(maxlen=256)  # (t_end, batch_seconds)
        self.dispatched = 0
        self.completed = 0
        self.errored = 0
        self.dead_lettered = 0
        # busy-fraction window state (main-loop tick only)
        self.tick_busy_s = 0.0
        self.tick_t = time.monotonic()

    def live_workers(self) -> list[_Worker]:
        return [
            w for w in self.workers
            if w.thread is not None and w.thread.is_alive() and not w.stop.is_set()
        ]

    def throughput_per_worker(self, window_s: float) -> float | None:
        """Batches/s one worker achieves (engine/pool.py's formula: the
        inverse mean batch duration over the recent window)."""
        now = time.monotonic()
        with self.lock:
            recent = [dur for (t, dur) in self.samples if t >= now - window_s]
        if not recent:
            return None
        mean_t = sum(recent) / len(recent)
        return 1.0 / mean_t if mean_t > 0 else None


_ABORTED = object()  # worker-loop sentinel: run is aborting, exit now


class PipelinedRunner(RunnerInterface):
    """Run all stages concurrently in thread pools on this host."""

    def __init__(
        self,
        *,
        raise_on_error: bool = True,
        replan_interval_s: float = 2.0,
        queue_capacity: int | None = None,
        batch_linger_s: float = 0.2,
        poll_interval_s: float = 0.02,
        thread_cap: int | None = None,
        metrics_port: int | None = None,
    ) -> None:
        self.raise_on_error = raise_on_error
        self.replan_interval_s = replan_interval_s
        self.queue_capacity = queue_capacity  # None = streaming-spec formula
        self.batch_linger_s = batch_linger_s
        self.poll_interval_s = poll_interval_s
        self.thread_cap = thread_cap or max(4, (os.cpu_count() or 1) * 2)
        self.metrics = get_metrics(metrics_port)
        # stage name -> summed process_data seconds (MFU accounting parity
        # with StreamingRunner's busy seconds / SequentialRunner's wall)
        self.stage_times: dict[str, float] = {}
        self.stage_counts: dict[str, dict] = {}
        self.pipeline_wall_s = 0.0
        # busy seconds of the LAST run only — stage_times accumulates across
        # runs (SequentialRunner parity), which would fabricate overlap
        self._last_run_busy_s = 0.0
        self.dlq = None
        self._abort = threading.Event()
        self._abort_lock = threading.Lock()
        self._abort_exc: BaseException | None = None

    # ------------------------------------------------------------------
    @property
    def overlap_frac(self) -> float:
        """Fraction of total host stage work hidden behind other stages:
        ``1 - wall / sum(stage busy seconds)``, clamped at 0. A strictly
        sequential execution scores 0 (wall == summed busy); a perfectly
        overlapped one approaches ``1 - max/sum``. This is the number bench
        emits as ``pipeline_overlap_frac``. Computed over the LAST ``run()``
        only (wall and busy from the same run)."""
        busy = self._last_run_busy_s
        if busy <= 0 or self.pipeline_wall_s <= 0:
            return 0.0
        return max(0.0, 1.0 - self.pipeline_wall_s / busy)

    # ------------------------------------------------------------------
    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        from cosmos_curate_tpu.observability.tracing import traced_span

        if not spec.stages:
            return list(spec.input_data) if spec.config.return_last_stage_outputs else None
        # the run-root span rides the contextvar stack; worker threads are
        # started under contextvars.copy_context() (see _start_worker), so
        # their batch spans parent onto it across the thread-pool hop
        with traced_span(
            "pipeline.run", runner="pipelined", stages=len(spec.stages)
        ):
            return self._run_pipelined(spec)

    def _run_pipelined(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        t_start = time.monotonic()
        self._abort.clear()
        self._abort_exc = None
        self.dlq = DeadLetterQueue()  # lazy: writes nothing unless a drop happens
        cfg = spec.config
        node = NodeInfo(
            node_id="local",
            num_cpus=cfg.num_cpus or float(os.cpu_count() or 1),
            num_tpu_chips=discover_tpu_chips(cfg, spec.stages),
        )
        self._node = node

        outputs: list[PipelineTask] = []
        outputs_lock = threading.Lock()

        def collect(tasks: list) -> None:
            if not cfg.return_last_stage_outputs:
                return
            with outputs_lock:
                outputs.extend(tasks)

        # stage i's input queue; queue 0 is pre-seeded and closed (inputs
        # are already materialized in RAM — backpressure matters BETWEEN
        # stages, where new payloads get created)
        queues = [
            _TaskQueue(self._queue_capacity(s, 1, cfg)) for s in spec.stages
        ]
        queues[0].set_capacity(max(queues[0].capacity, len(spec.input_data)))
        runtimes: list[_StageRuntime] = []
        for i, stage_spec in enumerate(spec.stages):
            if i + 1 < len(spec.stages):
                nxt = queues[i + 1]
                emit = lambda tasks, q=nxt: q.put_many(tasks, self._abort.is_set)
            else:
                emit = collect
            runtimes.append(_StageRuntime(i, stage_spec, queues[i], emit))
        queues[0].put_many(list(spec.input_data), self._abort.is_set)
        queues[0].close()

        budget = self._budget(node)
        self._apply_allocation(runtimes, self._plan(runtimes, budget), cfg)

        # live ops plane: snapshots + stall detection, on when run_split
        # (or an operator) exported CURATE_LIVE_STATUS_DIR; zero overhead
        # otherwise. Published from THIS loop — never the worker threads.
        from cosmos_curate_tpu.observability.live_status import LiveStatusPublisher

        publisher = LiveStatusPublisher.from_env(runner="pipelined")

        last_replan = time.monotonic()
        try:
            while not self._abort.is_set():
                for rt in runtimes:
                    if rt.finalized or not rt.in_q.drained:
                        continue
                    if any(w.thread is not None and w.thread.is_alive() for w in rt.workers):
                        continue
                    self._finalize_stage(rt)
                    if rt.idx + 1 < len(queues):
                        queues[rt.idx + 1].close()
                if runtimes[-1].finalized:
                    break
                now = time.monotonic()
                if now - last_replan >= self.replan_interval_s:
                    self._apply_allocation(runtimes, self._plan(runtimes, budget), cfg)
                    self._export_flow(runtimes)
                    last_replan = now
                if publisher is not None:
                    publisher.maybe_publish(
                        lambda: self._build_live_snapshot(runtimes)
                    )
                time.sleep(self.poll_interval_s)
        finally:
            # ANY exit path — normal, abort, or a foreign exception like
            # KeyboardInterrupt in the loop above — must unblock every
            # worker, or the joins below stall 30s per thread. close() is
            # idempotent; stop flags cover workers mid-linger.
            for q in queues:
                q.close()
            for rt in runtimes:
                for w in rt.workers:
                    w.stop.set()
            for rt in runtimes:
                for w in rt.workers:
                    if w.thread is not None:
                        w.thread.join(timeout=30.0)
            for rt in runtimes:
                if rt.finalized:
                    continue
                if any(
                    w.thread is not None and w.thread.is_alive() for w in rt.workers
                ):
                    # a wedged worker (cold compile, stuck decode) outlived
                    # the join grace: leaking its state beats racing
                    # destroy() against a live process_data on the same
                    # shared stage instance
                    logger.error(
                        "stage %s: worker still running after abort grace; "
                        "skipping destroy()", rt.stage.name,
                    )
                    rt.finalized = True
                    continue
                self._finalize_stage(rt)
            self.pipeline_wall_s = time.monotonic() - t_start
            self._export_flow(runtimes)  # final gauge tick (short runs too)
            self._record_run_stats(runtimes)
            if publisher is not None:
                try:
                    publisher.finalize(self._build_live_snapshot(runtimes))
                except Exception:
                    logger.exception("final live-status publish failed")

        if self._abort_exc is not None:
            raise self._abort_exc
        return outputs if cfg.return_last_stage_outputs else None

    # ------------------------------------------------------------------
    # worker side
    def _worker_loop(self, rt: _StageRuntime, w: _Worker) -> None:
        if not self._ensure_setup(rt, w):
            return
        bs = max(1, rt.stage.batch_size)
        attempts = max(1, rt.spec.num_run_attempts)

        def should_stop() -> bool:
            return self._abort.is_set() or w.stop.is_set()

        while True:
            batch = rt.in_q.get_batch(bs, should_stop, self.batch_linger_s)
            if batch is None:
                return
            with rt.lock:
                rt.dispatched += 1
                batch_id = rt.next_batch_id
                rt.next_batch_id += 1
            result = self._run_batch(rt, batch, batch_id, attempts, w.meta.worker_id)
            if result is _ABORTED:
                return
            if result:
                rt.emit(result)

    def _run_batch(
        self, rt: _StageRuntime, batch: list, batch_id: int, attempts: int,
        worker_id: str = "",
    ):
        from cosmos_curate_tpu.observability.stage_timer import record_stage_busy
        from cosmos_curate_tpu.observability.tracing import traced_span

        stage = rt.stage
        for attempt in range(attempts):
            t0 = time.monotonic()
            with rt.lock:
                # live-status visibility: registered BEFORE the chaos sites
                # and process_data, so a hang shows as an aging in-flight
                # batch from its first stuck second
                rt.inflight[batch_id] = {
                    "started": t0, "worker": worker_id, "attempt": attempt + 1,
                }
            try:
                chaos.fire(chaos.SITE_WORKER_CRASH)  # kind=crash: os._exit
                chaos.fire(chaos.SITE_WORKER_HANG)  # kind=hang: stuck batch
                with traced_span(
                    f"stage.{stage.name}.process", batch_size=len(batch)
                ):
                    result = stage.process_data(batch)
                if result is not None and not isinstance(result, list):
                    # contract violation, not a batch failure: deterministic
                    # stage bugs must surface (SequentialRunner parity —
                    # raises regardless of raise_on_error), never burn
                    # retries or masquerade as a dead-lettered batch
                    self._trigger_abort(
                        TypeError(
                            f"stage {stage.name}.process_data must return "
                            f"list[PipelineTask] or None, got {type(result).__name__}"
                        )
                    )
                    return _ABORTED
                elapsed = time.monotonic() - t0
                with rt.lock:
                    rt.busy_s += elapsed
                    rt.samples.append((time.monotonic(), elapsed))
                    rt.completed += 1
                record_stage_busy(stage.name, elapsed)
                self.metrics.observe_result(
                    stage.name, elapsed, 0.0, len(result or [])
                )
                return result or []
            except Exception as e:
                with rt.lock:
                    rt.busy_s += time.monotonic() - t0
                self.metrics.observe_error(stage.name)
                if attempt + 1 < attempts:
                    logger.warning(
                        "stage %s batch %d failed (attempt %d/%d), retrying: %s",
                        stage.name, batch_id, attempt + 1, attempts, e,
                    )
                    continue
                if self.raise_on_error:
                    self._trigger_abort(e)
                    return _ABORTED
                with rt.lock:
                    rt.errored += 1
                logger.exception(
                    "stage %s batch %d failed permanently; dropping %d tasks",
                    stage.name, batch_id, len(batch),
                )
                self._dead_letter(rt, batch_id, batch, attempts)
                return []
            finally:
                with rt.lock:
                    rt.inflight.pop(batch_id, None)
        return []  # unreachable; attempts >= 1

    def _ensure_setup(self, rt: _StageRuntime, w: _Worker) -> bool:
        claim = False
        with rt.lock:
            if rt.setup_state == "pending":
                rt.setup_state = "running"
                claim = True
        if claim:
            from cosmos_curate_tpu.observability.tracing import traced_span

            try:
                with traced_span(f"stage.{rt.stage.name}.setup"):
                    rt.stage.setup_on_node(self._node, w.meta)
                    rt.stage.setup(w.meta)
                rt.setup_state = "ok"
                return True
            except Exception as e:
                rt.setup_state = "failed"
                self._trigger_abort(e)
                return False
            finally:
                rt.setup_done.set()
        while not rt.setup_done.wait(0.1):
            if self._abort.is_set():
                return False
        return rt.setup_state == "ok"

    def _trigger_abort(self, exc: BaseException) -> None:
        with self._abort_lock:
            if self._abort_exc is None:  # first failure wins
                self._abort_exc = exc
        self._abort.set()

    def _dead_letter(self, rt: _StageRuntime, batch_id: int, tasks: list, attempts: int) -> None:
        """Persist a permanently-dropped batch like the streaming engine
        does. Never raises — DLQ failure degrades to the log-only drop."""
        if record_exhausted_batch(
            self.dlq,
            stage_name=rt.stage.name,
            batch_id=batch_id,
            tasks=tasks,
            attempts=attempts,
            error=traceback.format_exc(),
        ):
            with rt.lock:
                rt.dead_lettered += 1

    # ------------------------------------------------------------------
    # planning / scaling
    def _budget(self, node: NodeInfo):
        return Budget(cpus=node.num_cpus, tpus=float(node.num_tpu_chips))

    def _plan(self, runtimes: list[_StageRuntime], budget) -> list[int]:
        states = []
        for rt in runtimes:
            spec = rt.spec
            if _single_worker_only(spec.stage):
                spec = replace(spec, num_workers=1)
            elif spec.num_workers is None:
                cap = spec.max_workers
                spec = replace(
                    spec,
                    max_workers=min(cap, self.thread_cap) if cap else self.thread_cap,
                )
            states.append(
                StageScaleState(
                    spec=spec,
                    current_workers=len(rt.live_workers()),
                    throughput_per_worker=rt.throughput_per_worker(window_s=60.0),
                    queued=len(rt.in_q),
                )
            )
        return plan_allocation(states, budget)

    def _apply_allocation(self, runtimes: list[_StageRuntime], targets: list[int], cfg) -> None:
        for rt, target in zip(runtimes, targets):
            rt.workers = [
                w for w in rt.workers if w.thread is not None and w.thread.is_alive()
            ]
            if rt.finalized:
                continue
            if rt.in_q.drained and rt.setup_state != "pending":
                # stage is winding down — no new workers. A never-started
                # stage (empty input) still gets one below, so the
                # setup→destroy lifecycle runs for every stage, exactly as
                # the sequential runner guarantees.
                continue
            target = max(1, target)
            live = rt.live_workers()
            for _ in range(target - len(live)):
                self._start_worker(rt)
            if len(live) > target:
                for w in live[target:]:  # scale down: drain-and-exit
                    w.stop.set()
            rt.in_q.set_capacity(self._queue_capacity(rt.spec, max(1, target), cfg))

    def _queue_capacity(self, spec: StageSpec, workers: int, cfg) -> int:
        if self.queue_capacity is not None:
            return self.queue_capacity
        s = cfg.streaming
        return max(s.max_queued_lower_bound, int(s.max_queued_multiplier * workers))

    def _start_worker(self, rt: _StageRuntime) -> None:
        widx = rt.next_worker_idx
        rt.next_worker_idx += 1
        meta = WorkerMetadata(
            worker_id=f"{rt.stage.name}-pipe-{widx}",
            stage_name=rt.stage.name,
            node=self._node,
            allocation=rt.stage.resources,
        )
        w = _Worker(meta=meta)
        # carry the caller's context (the run-root trace span) into the
        # worker thread: contextvars survive this hop, threading.local
        # would not
        ctx = contextvars.copy_context()
        w.thread = threading.Thread(
            target=ctx.run,
            args=(self._worker_loop, rt, w),
            daemon=True,
            name=meta.worker_id,
        )
        rt.workers.append(w)
        w.thread.start()

    # ------------------------------------------------------------------
    def _finalize_stage(self, rt: _StageRuntime) -> None:
        if rt.setup_state == "ok" and not rt.destroyed:
            rt.destroyed = True
            try:
                rt.stage.destroy()
            except Exception:
                logger.exception("stage %s destroy failed", rt.stage.name)
        rt.finalized = True

    def _export_flow(self, runtimes: list[_StageRuntime]) -> None:
        """Per-stage queue-depth and busy-fraction gauges, one tick."""
        from cosmos_curate_tpu.observability.stage_timer import record_stage_flow

        now = time.monotonic()
        for rt in runtimes:
            workers = len(rt.live_workers())
            with rt.lock:
                busy = rt.busy_s
            dt = now - rt.tick_t
            window_busy = busy - rt.tick_busy_s
            rt.tick_busy_s = busy
            rt.tick_t = now
            frac = (
                min(1.0, window_busy / (dt * max(1, workers))) if dt > 0 else 0.0
            )
            record_stage_flow(
                rt.stage.name,
                queue_depth=len(rt.in_q),
                busy_frac=frac,
                workers=workers,
            )

    def _build_live_snapshot(self, runtimes: list[_StageRuntime]) -> dict:
        """One live-status snapshot (observability/live_status.py) from
        state the runner already keeps — counters, the throughput sample
        window, and the in-flight registry. Bounded and lock-brief."""
        from cosmos_curate_tpu.observability.live_status import (
            MAX_INFLIGHT_PER_STAGE,
        )

        now = time.monotonic()
        stages: dict[str, dict] = {}
        for rt in runtimes:
            workers = len(rt.live_workers())
            with rt.lock:
                inflight = sorted(
                    rt.inflight.items(), key=lambda kv: kv[1]["started"]
                )[:MAX_INFLIGHT_PER_STAGE]
                durs = sorted(d for (_t, d) in rt.samples)
                busy = rt.busy_s
                counts = (rt.dispatched, rt.completed, rt.errored, rt.dead_lettered)
            # busy fraction over the window since the last replan tick —
            # read-only against the tick state _export_flow owns
            dt = now - rt.tick_t
            frac = (
                min(1.0, (busy - rt.tick_busy_s) / (dt * max(1, workers)))
                if dt > 0
                else 0.0
            )
            stages[rt.stage.name] = {
                "queue_depth": len(rt.in_q),
                "busy_frac": round(frac, 4),
                "workers": workers,
                "dispatched": counts[0],
                "completed": counts[1],
                "errored": counts[2],
                "dead_lettered": counts[3],
                "finished": rt.finalized,
                "p50_s": round(durs[len(durs) // 2], 4) if durs else 0.0,
                "p99_s": (
                    round(durs[min(len(durs) - 1, int(len(durs) * 0.99))], 4)
                    if durs
                    else 0.0
                ),
                "inflight": [
                    {
                        "batch_id": bid,
                        "age_s": round(now - info["started"], 3),
                        "attempt": info.get("attempt", 1),
                        "worker": info.get("worker", ""),
                    }
                    for bid, info in inflight
                ],
            }
        return {"stages": stages}

    def _record_run_stats(self, runtimes: list[_StageRuntime]) -> None:
        self.stage_counts = {}
        self._last_run_busy_s = 0.0
        for rt in runtimes:
            with rt.lock:
                self._last_run_busy_s += rt.busy_s
                self.stage_times[rt.stage.name] = (
                    self.stage_times.get(rt.stage.name, 0.0) + rt.busy_s
                )
                self.stage_counts[rt.stage.name] = {
                    "dispatched": rt.dispatched,
                    "completed": rt.completed,
                    "errored": rt.errored,
                    "dead_lettered": rt.dead_lettered,
                    "workers": rt.next_worker_idx,
                }
            logger.info(
                "stage %s: %d dispatched, %d completed, %d errored, "
                "%d dead-lettered (%.2fs busy, %d workers)",
                rt.stage.name,
                self.stage_counts[rt.stage.name]["dispatched"],
                self.stage_counts[rt.stage.name]["completed"],
                self.stage_counts[rt.stage.name]["errored"],
                self.stage_counts[rt.stage.name]["dead_lettered"],
                rt.busy_s,
                rt.next_worker_idx,
            )
        # export the stage-overlap headline as a real gauge (bench used to
        # be the only reader of this number)
        self.metrics.set_overlap_frac(self.overlap_frac)
        if self.dlq is not None and self.dlq.recorded:
            logger.error(
                "%d dropped batch(es) persisted to the dead-letter queue: "
                "%s — inspect with `cosmos-curate-tpu dlq list`",
                self.dlq.recorded, self.dlq.run_dir,
            )


def _single_worker_only(stage) -> bool:
    """Device stages (pinned model dispatch or TPU resources) and stages
    not annotated ``thread_safe`` run with exactly one worker thread."""
    if stage.resources.uses_tpu:
        return True
    model = stage.model
    if model is not None and getattr(model, "pin_to_single_worker", True):
        return True
    return not getattr(stage, "thread_safe", False)
