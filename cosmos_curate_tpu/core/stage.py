"""Stage contracts: resources, lifecycle hooks, per-stage scheduling knobs.

Equivalent surface of the reference's ``CuratorStage``/``CuratorStageSpec``/
``Resources`` (cosmos_curate/core/interfaces/stage_interface.py) and the
cosmos-xenna ``Stage``/``StageSpec`` they wrap (SURVEY.md §1).

TPU-first deltas from the reference:

- ``Resources.tpus`` counts *chips of the local TPU host* instead of
  fractional CUDA devices. Fractional-GPU packing (0.25 GPU/worker) has no TPU
  analogue; its equivalent here is batch aggregation — one engine worker per
  host owns all local chips via a mesh (``entire_tpu_host=True``) and is fed
  by many CPU prep workers. The autoscaler treats ``tpu`` as a resource type
  alongside ``cpu`` (SURVEY.md §2.7).
- No conda/pixi multi-env machinery: the TPU stack collapses to one process
  image, so ``env_name`` is advisory metadata only (kept so pipelines can
  still declare isolation intent; the engine may map it to separate worker
  process pools).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field, replace
from typing import TYPE_CHECKING, Generic, TypeVar

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.tasks import PipelineTask

if TYPE_CHECKING:
    from cosmos_curate_tpu.parallel.mesh import MeshSpec

T = TypeVar("T", bound=PipelineTask)
V = TypeVar("V", bound=PipelineTask)


@dataclass(frozen=True)
class Resources:
    """Per-worker resource request.

    ``cpus`` may be fractional (IO-bound stages request e.g. 0.25 so many
    workers pack onto one core). ``tpus`` is in chips; ``entire_tpu_host``
    claims every chip on whichever host the worker lands on (the worker then
    builds a local ``Mesh`` over them).
    """

    cpus: float = 1.0
    tpus: float = 0.0
    entire_tpu_host: bool = False
    memory_gb: float = 0.0

    def __post_init__(self) -> None:
        if self.cpus < 0 or self.tpus < 0 or self.memory_gb < 0:
            raise ValueError(f"negative resource request: {self}")

    @property
    def uses_tpu(self) -> bool:
        return self.tpus > 0 or self.entire_tpu_host


@dataclass(frozen=True)
class NodeInfo:
    """Identity of the host a worker is placed on."""

    node_id: str = "local"
    num_cpus: float = 1.0
    num_tpu_chips: int = 0


@dataclass(frozen=True)
class WorkerMetadata:
    """Identity + allocation of one worker within a stage pool."""

    worker_id: str = "worker-0"
    stage_name: str = ""
    node: NodeInfo = field(default_factory=NodeInfo)
    allocation: Resources = field(default_factory=Resources)
    # Chip indices on the local host assigned to this worker (empty for CPU
    # stages; all local chips when entire_tpu_host).
    tpu_chip_ids: tuple[int, ...] = ()


class Stage(Generic[T, V], abc.ABC):
    """A pipeline stage: a stateful worker template.

    Lifecycle inside each worker (SURVEY.md §3.2):
      ``setup_on_node`` (once per host) → ``setup`` (once per worker) →
      ``process_data`` repeatedly (the hot loop) → ``destroy``.
    """

    @property
    def name(self) -> str:
        # observability wrappers subclass dynamically and stash the original
        # name here so logs/metrics/artifacts keep the user-visible name
        return getattr(self, "_display_name", type(self).__name__)

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0)

    @property
    def model(self) -> ModelInterface | None:
        """Model this stage drives; engine pre-stages weights per node."""
        return None

    @property
    def env_name(self) -> str:
        """Advisory execution-environment tag (see module docstring)."""
        return "default"

    @property
    def mesh_spec(self) -> "MeshSpec | None":
        """Declared device-mesh geometry this stage's model builds
        (parallel/mesh.py); ``None`` = no mesh, or discovered at run time.
        Declaring it lets the ``run_pipeline`` pre-flight reject a mesh
        that cannot tile ``ClusterShape.num_tpu_chips`` before any worker
        spawns (and ``lint --shard-check`` validate axis names and
        divisibility device-free)."""
        return None

    @property
    def batch_size(self) -> int:
        """How many tasks ``process_data`` receives per call."""
        return 1

    @property
    def node_affinity(self) -> str | None:
        """Cross-host placement hint for the per-node planner
        (engine/autoscaler.plan_node_allocation). ``None`` (default) lets
        the planner fan workers across any node with CPU budget;
        ``"driver"`` pins every worker to the driver node — for stages
        whose side effects must land driver-local (e.g. a writer flushing
        to a driver-mounted path). TPU stages are implicitly driver-pinned
        (chips belong to the engine process) and need no hint."""
        return None

    @property
    def thread_safe(self) -> bool:
        """True when concurrent ``process_data`` calls on DISJOINT batches
        are safe — no cross-call mutable state on ``self`` (per-task mutation
        is fine; every batch owns its tasks). The pipelined runner
        (core/pipelined_runner.py) only fans a stage out across worker
        threads when this is declared; process-pool runners are unaffected
        (each worker process owns a private stage copy). Default False:
        an unannotated stage runs single-worker."""
        return False

    def setup_on_node(self, node: NodeInfo, worker: WorkerMetadata) -> None:
        """Once per host before any worker setup (e.g. weight download)."""

    def setup(self, worker: WorkerMetadata) -> None:
        """Once per worker (load model, open handles)."""
        model = self.model
        if model is not None:
            model.setup()

    @abc.abstractmethod
    def process_data(self, tasks: list[T]) -> list[V] | None:
        """Process a batch of tasks; may emit a different number of tasks
        than received (dynamic chunking). ``None`` drops the batch."""

    def destroy(self) -> None:
        """Worker teardown (flush artifacts, free device memory)."""


@dataclass
class StageSpec(Generic[T, V]):
    """A stage plus its scheduling knobs.

    Mirrors the reference's ``CuratorStageSpec``/xenna ``StageSpec``
    (stage_interface.py:191-214): worker-count bounds, retries, over-
    provisioning, and scheduled worker recycling (the leak guard for
    long-running accelerator workers, pipeline_interface.py:187-219).
    """

    stage: Stage[T, V]
    num_workers: int | None = None  # fixed pool size; None = autoscale
    num_workers_per_node: int | None = None
    min_workers: int = 1
    max_workers: int | None = None
    num_run_attempts: int = 1
    # Wall-clock deadline for ONE batch execution (dispatch → result), in
    # seconds; None disables. On expiry the engine kills the offending
    # worker (a hung decoder/socket never returns on its own), charges the
    # batch's worker-death budget and requeues it — see
    # docs/FAULT_TOLERANCE.md. Enforced for process-pool workers (local via
    # the runner, remote via the node agent's watchdog); in-process TPU
    # workers cannot be killed and ignore it.
    batch_timeout_s: float | None = None
    over_provision_factor: float | None = None
    # None = unset (heuristic defaults applied); 0 = never recycle.
    worker_max_lifetime_m: int | None = None
    worker_restart_interval_m: int = 1
    # Fraction of inputs to record for offline replay (0 disables).
    stage_save_sample_rate: float = 0.0

    @property
    def name(self) -> str:
        return self.stage.name


def fill_default_lifetimes(spec: StageSpec) -> StageSpec:
    """Apply the reference's worker-lifetime heuristics
    (pipeline_interface.py:187-219): TPU stages recycle at 120 min, CPU
    stages at 60 min, IO stages (<1 CPU, no TPU) never. An explicit
    ``worker_max_lifetime_m`` (including 0 = never) is preserved; the
    caller's spec is not mutated."""
    if spec.worker_max_lifetime_m is not None:
        return spec
    res = spec.stage.resources
    if res.uses_tpu:
        lifetime, interval = 120, 5
    elif res.cpus >= 1:
        lifetime, interval = 60, 1
    else:  # IO stage — never recycle.
        lifetime, interval = 0, spec.worker_restart_interval_m
    return replace(
        spec, worker_max_lifetime_m=lifetime, worker_restart_interval_m=interval
    )
