"""Runner abstraction + the in-process SequentialRunner.

Equivalent of the reference's ``RunnerInterface``/``XennaRunner``
(cosmos_curate/core/interfaces/runner_interface.py:37-183) and its test
``SequentialRunner`` (tests/utils/sequential_runner.py:27-69) — promoted here
to a first-class citizen because it is also the right way to run small local
jobs on a single host without the streaming engine.
"""

from __future__ import annotations

import abc
import os
import time

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.core.pipeline import PipelineSpec
from cosmos_curate_tpu.core.stage import NodeInfo, WorkerMetadata
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RunnerInterface(abc.ABC):
    """Executes a ``PipelineSpec``; returns last-stage outputs (or None)."""

    @abc.abstractmethod
    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None: ...


class SequentialRunner(RunnerInterface):
    """Run every stage in-process, stage by stage, no parallelism.

    Exact lifecycle per stage: ``setup_on_node`` → ``setup`` →
    ``process_data`` over batches → ``destroy``. Honors ``batch_size`` and
    dynamic chunking (a stage may emit more or fewer tasks than it
    received). This is both the test harness and the minimal local runner.
    """

    def __init__(self, *, raise_on_error: bool = True) -> None:
        self.raise_on_error = raise_on_error
        # stage name -> wall seconds of the last run (MFU accounting reads
        # this; benchmarks/split_benchmark.py)
        self.stage_times: dict[str, float] = {}
        # DLQ parity with the engine: permanently dropped batches persist
        # (engine/dead_letter.py); lazy — a clean run creates nothing
        self.dlq = None
        self.dead_lettered = 0

    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        from cosmos_curate_tpu.observability.live_status import LiveStatusPublisher
        from cosmos_curate_tpu.observability.tracing import traced_span

        # fresh run-scoped DLQ state (run_id is fixed at DLQ construction,
        # so reusing one across runs would file run 2's drops under run 1)
        self.dlq = None
        self.dead_lettered = 0
        node = NodeInfo(node_id="local")
        tasks: list[PipelineTask] = list(spec.input_data)
        # live ops plane: snapshots publish between batches (this runner is
        # single-threaded, so a hung batch shows as a STALE snapshot whose
        # last entry is the in-flight batch — `top` flags the staleness)
        self._publisher = LiveStatusPublisher.from_env(runner="sequential")
        self._live_stages: dict[str, dict] = {
            s.stage.name: {"queue_depth": 0, "workers": 0, "completed": 0,
                           "errored": 0, "dead_lettered": 0, "busy_frac": 0.0,
                           "inflight": []}
            for s in spec.stages
        }
        try:
            with traced_span(
                "pipeline.run", runner="sequential", stages=len(spec.stages)
            ):
                for stage_spec in spec.stages:
                    tasks = self._run_stage(stage_spec, node, tasks)
        finally:
            if self._publisher is not None:
                try:
                    self._publisher.finalize({"stages": dict(self._live_stages)})
                except Exception:
                    logger.exception("final live-status publish failed")
        return tasks if spec.config.return_last_stage_outputs else None

    def _run_stage(self, stage_spec, node, tasks: list) -> list:
        from cosmos_curate_tpu.observability.tracing import traced_span

        stage = stage_spec.stage
        meta = WorkerMetadata(
            worker_id=f"{stage.name}-seq-0",
            stage_name=stage.name,
            node=node,
            allocation=stage.resources,
        )
        t0 = time.monotonic()
        out: list[PipelineTask] = []
        with traced_span(f"stage.{stage.name}", stage=stage.name):
            with traced_span(f"stage.{stage.name}.setup"):
                stage.setup_on_node(node, meta)
                stage.setup(meta)
            bs = max(1, stage.batch_size)
            live = getattr(self, "_live_stages", {}).get(stage.name)
            try:
                for i in range(0, len(tasks), bs):
                    batch = tasks[i : i + bs]
                    # per-BATCH baseline: dead_lettered is a run-global
                    # counter, so a drop in an earlier stage must not
                    # misclassify this stage's next success
                    dl_before = self.dead_lettered
                    if live is not None and self._publisher is not None:
                        live.update(
                            queue_depth=max(0, len(tasks) - i - len(batch)),
                            workers=1, busy_frac=1.0,
                            inflight=[{
                                "batch_id": i // bs, "age_s": 0.0, "attempt": 1,
                                "worker": f"{stage.name}-seq-0",
                            }],
                        )
                        self._publisher.maybe_publish(
                            lambda: {"stages": dict(self._live_stages)}
                        )
                    for attempt in range(max(1, stage_spec.num_run_attempts)):
                        try:
                            chaos.fire(chaos.SITE_WORKER_CRASH)  # kind=crash: os._exit
                            chaos.fire(chaos.SITE_WORKER_HANG)  # kind=hang: stuck batch
                            with traced_span(
                                f"stage.{stage.name}.process", batch_size=len(batch)
                            ):
                                result = stage.process_data(batch)
                            break
                        except Exception:
                            if attempt + 1 >= max(1, stage_spec.num_run_attempts):
                                if self.raise_on_error:
                                    raise
                                logger.exception(
                                    "stage %s failed on batch %d; dropping", stage.name, i
                                )
                                self._dead_letter(stage.name, i, batch, attempt + 1)
                                result = None
                    if live is not None:
                        live["inflight"] = []
                        live["busy_frac"] = 0.0
                        # a dropped batch bumped the run-global DLQ counter
                        # inside _dead_letter; everything else completed (a
                        # legit None result is a no-output success)
                        if self.dead_lettered > dl_before:
                            live["errored"] += 1
                            live["dead_lettered"] += self.dead_lettered - dl_before
                        else:
                            live["completed"] += 1
                    if result is None:
                        continue
                    if not isinstance(result, list):
                        raise TypeError(
                            f"stage {stage.name}.process_data must return "
                            f"list[PipelineTask] or None, got {type(result).__name__}"
                        )
                    out.extend(result)
            finally:
                stage.destroy()
        stage_s = time.monotonic() - t0
        self.stage_times[stage.name] = self.stage_times.get(stage.name, 0.0) + stage_s
        logger.info(
            "stage %s: %d -> %d tasks in %.2fs", stage.name, len(tasks), len(out), stage_s
        )
        return out

    def _dead_letter(self, stage_name: str, batch_id: int, tasks: list, attempts: int) -> None:
        """Persist a dropped batch to the durable DLQ — local runs get the
        same recoverability the streaming engine's drop path has. Never
        raises: DLQ failure degrades to the log-only drop above."""
        import traceback

        try:
            from cosmos_curate_tpu.engine.dead_letter import (
                DeadLetterQueue,
                record_exhausted_batch,
            )
        except ImportError:
            return
        if self.dlq is None:
            self.dlq = DeadLetterQueue()
        if record_exhausted_batch(
            self.dlq,
            stage_name=stage_name,
            batch_id=batch_id,
            tasks=tasks,
            attempts=attempts,
            error=traceback.format_exc(),
        ):
            self.dead_lettered += 1


def default_runner() -> RunnerInterface:
    """Production runner selection.

    ``CURATE_RUNNER=sequential|pipelined|engine`` forces a backend. Without
    the override: multi-host runs (a remote data plane is configured via
    ``CURATE_ENGINE_DRIVER_PORT``) use the streaming engine, whose process
    pools span node agents; single-host runs default to the
    ``PipelinedRunner`` — stage-overlapped thread pools that keep the device
    fed by host stages without the engine's worker-spawn overhead.
    """
    choice = os.environ.get("CURATE_RUNNER", "").strip().lower()
    known = ("", "auto", "sequential", "pipelined", "engine", "streaming", "map")
    if choice not in known:
        # a typo must not silently land on the multi-threaded default —
        # an operator forcing `sequential` to debug threading needs to
        # KNOW when the override didn't take
        raise ValueError(
            f"unknown CURATE_RUNNER={choice!r}; expected one of {known[1:]}"
        )
    if choice == "sequential":
        return SequentialRunner()
    if choice == "map":
        from cosmos_curate_tpu.core.map_runner import MapRunner

        return MapRunner()
    if choice in ("engine", "streaming") or (
        choice in ("", "auto") and os.environ.get("CURATE_ENGINE_DRIVER_PORT")
    ):
        try:
            from cosmos_curate_tpu.engine.runner import StreamingRunner
        except ImportError as e:
            # Only the engine itself being absent may degrade; a broken
            # engine module must surface, not silently lose throughput.
            if e.name is None or not e.name.startswith("cosmos_curate_tpu.engine"):
                raise
            logger.warning("streaming engine unavailable; using SequentialRunner")
            return SequentialRunner()
        return StreamingRunner()
    try:
        # the pipelined runner reuses the engine's autoscaler/metrics/DLQ,
        # so engine absence degrades it too
        from cosmos_curate_tpu.core.pipelined_runner import PipelinedRunner
    except ImportError as e:
        if e.name is None or not e.name.startswith(
            ("cosmos_curate_tpu.engine", "cosmos_curate_tpu.core.pipelined_runner")
        ):
            raise
        logger.warning("pipelined runner unavailable; using SequentialRunner")
        return SequentialRunner()
    # production semantics match the streaming engine: an exhausted batch is
    # dead-lettered and the run CONTINUES — one poison batch must not void
    # hours of curation. Tests wanting fail-fast construct the runner
    # directly (raise_on_error defaults to True there, like SequentialRunner).
    return PipelinedRunner(raise_on_error=False)
