"""Runner abstraction + the in-process SequentialRunner.

Equivalent of the reference's ``RunnerInterface``/``XennaRunner``
(cosmos_curate/core/interfaces/runner_interface.py:37-183) and its test
``SequentialRunner`` (tests/utils/sequential_runner.py:27-69) — promoted here
to a first-class citizen because it is also the right way to run small local
jobs on a single host without the streaming engine.
"""

from __future__ import annotations

import abc
import time

from cosmos_curate_tpu.core.pipeline import PipelineSpec
from cosmos_curate_tpu.core.stage import NodeInfo, WorkerMetadata
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RunnerInterface(abc.ABC):
    """Executes a ``PipelineSpec``; returns last-stage outputs (or None)."""

    @abc.abstractmethod
    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None: ...


class SequentialRunner(RunnerInterface):
    """Run every stage in-process, stage by stage, no parallelism.

    Exact lifecycle per stage: ``setup_on_node`` → ``setup`` →
    ``process_data`` over batches → ``destroy``. Honors ``batch_size`` and
    dynamic chunking (a stage may emit more or fewer tasks than it
    received). This is both the test harness and the minimal local runner.
    """

    def __init__(self, *, raise_on_error: bool = True) -> None:
        self.raise_on_error = raise_on_error
        # stage name -> wall seconds of the last run (MFU accounting reads
        # this; benchmarks/split_benchmark.py)
        self.stage_times: dict[str, float] = {}

    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        node = NodeInfo(node_id="local")
        tasks: list[PipelineTask] = list(spec.input_data)
        for stage_spec in spec.stages:
            stage = stage_spec.stage
            meta = WorkerMetadata(
                worker_id=f"{stage.name}-seq-0",
                stage_name=stage.name,
                node=node,
                allocation=stage.resources,
            )
            t0 = time.monotonic()
            from cosmos_curate_tpu.observability.tracing import traced_span

            with traced_span(f"stage.{stage.name}.setup"):
                stage.setup_on_node(node, meta)
                stage.setup(meta)
            out: list[PipelineTask] = []
            bs = max(1, stage.batch_size)
            try:
                for i in range(0, len(tasks), bs):
                    batch = tasks[i : i + bs]
                    for attempt in range(max(1, stage_spec.num_run_attempts)):
                        try:
                            with traced_span(
                                f"stage.{stage.name}.process", batch_size=len(batch)
                            ):
                                result = stage.process_data(batch)
                            break
                        except Exception:
                            if attempt + 1 >= max(1, stage_spec.num_run_attempts):
                                if self.raise_on_error:
                                    raise
                                logger.exception(
                                    "stage %s failed on batch %d; dropping", stage.name, i
                                )
                                result = None
                    if result is None:
                        continue
                    if not isinstance(result, list):
                        raise TypeError(
                            f"stage {stage.name}.process_data must return "
                            f"list[PipelineTask] or None, got {type(result).__name__}"
                        )
                    out.extend(result)
            finally:
                stage.destroy()
            stage_s = time.monotonic() - t0
            self.stage_times[stage.name] = self.stage_times.get(stage.name, 0.0) + stage_s
            logger.info(
                "stage %s: %d -> %d tasks in %.2fs", stage.name, len(tasks), len(out), stage_s
            )
            tasks = out
        return tasks if spec.config.return_last_stage_outputs else None


def default_runner() -> RunnerInterface:
    """The production runner: streaming engine if usable, else sequential."""
    try:
        from cosmos_curate_tpu.engine.runner import StreamingRunner
    except ImportError as e:
        # Only the engine itself being absent may degrade; a broken engine
        # module must surface, not silently fall back to 1/N throughput.
        if e.name is None or not e.name.startswith("cosmos_curate_tpu.engine"):
            raise
        logger.warning("streaming engine unavailable; using SequentialRunner")
        return SequentialRunner()
    return StreamingRunner()
