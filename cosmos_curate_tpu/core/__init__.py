"""Core pipeline contracts: tasks, stages, models, runners.

TPU-equivalent of the reference's core/interfaces/ layer
(cosmos_curate/core/interfaces/*.py) plus the engine-facing surface the
reference imports from cosmos-xenna (SURVEY.md §1).
"""

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.pipeline import (
    ExecutionMode,
    PipelineConfig,
    PipelineSpec,
    StreamingSpec,
    run_pipeline,
)
from cosmos_curate_tpu.core.runner import RunnerInterface, SequentialRunner
from cosmos_curate_tpu.core.stage import (
    NodeInfo,
    Resources,
    Stage,
    StageSpec,
    WorkerMetadata,
)
from cosmos_curate_tpu.core.tasks import PipelineTask

__all__ = [
    "ExecutionMode",
    "ModelInterface",
    "NodeInfo",
    "PipelineConfig",
    "PipelineSpec",
    "PipelineTask",
    "Resources",
    "RunnerInterface",
    "SequentialRunner",
    "Stage",
    "StageSpec",
    "StreamingSpec",
    "WorkerMetadata",
    "run_pipeline",
]
