"""MapRunner: stage-by-stage process-pool execution backend.

The reference keeps a second execution backend beside the xenna streaming
engine — a Ray-Data map-batches pipeline with simpler barrier semantics
(cosmos_curate/pipelines/video/ray_data/, SURVEY.md §2.4 "Ray-Data alt
backend"). This is that alternative for the TPU stack: each stage runs to
completion over all tasks before the next starts (a barrier, unlike the
StreamingRunner's continuous flow), with CPU stages fanned out over a
process pool and accelerator stages kept in-process (the TPU is owned by
exactly one process).

Semantics:
- lifecycle per stage: worker processes run ``setup_on_node`` → ``setup``
  once (pool initializer), then ``process_data`` per batch; ``destroy``
  runs at pool shutdown in each worker.
- per-batch retries honor ``StageSpec.num_run_attempts``; a failing batch
  is dropped (raise_on_error=False) or aborts the run.
- ``stage_times`` matches the other runners for MFU/bench accounting.
"""

from __future__ import annotations

import atexit
import os
import pickle
import time
from concurrent.futures import ProcessPoolExecutor

from cosmos_curate_tpu.core.pipeline import PipelineSpec
from cosmos_curate_tpu.core.runner import RunnerInterface, SequentialRunner
from cosmos_curate_tpu.core.stage import NodeInfo, WorkerMetadata
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_WORKER_STAGE = None


def _worker_init(stage_bytes: bytes, stage_name: str) -> None:
    global _WORKER_STAGE
    stage = pickle.loads(stage_bytes)
    node = NodeInfo(node_id="local")
    meta = WorkerMetadata(
        worker_id=f"{stage_name}-map-{os.getpid()}",
        stage_name=stage_name,
        node=node,
        allocation=stage.resources,
    )
    stage.setup_on_node(node, meta)
    stage.setup(meta)
    _WORKER_STAGE = stage
    atexit.register(stage.destroy)


def _worker_process(batch_bytes: bytes) -> bytes:
    batch = pickle.loads(batch_bytes)
    result = _WORKER_STAGE.process_data(batch)
    if result is not None and not isinstance(result, list):
        raise TypeError(
            f"stage {_WORKER_STAGE.name}.process_data must return "
            f"list[PipelineTask] or None, got {type(result).__name__}"
        )
    return pickle.dumps(result)


class MapRunner(RunnerInterface):
    """Barrier-per-stage map execution over a process pool."""

    def __init__(
        self, *, max_workers: int | None = None, raise_on_error: bool = True
    ) -> None:
        self.max_workers = max_workers
        self.raise_on_error = raise_on_error
        self.stage_times: dict[str, float] = {}

    def _stage_workers(self, stage_spec) -> int:
        if self.max_workers is not None:
            cap = self.max_workers
        else:
            cap = max(1, (os.cpu_count() or 1))
        wanted = stage_spec.num_workers or cap
        return max(1, min(wanted, cap))

    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        tasks: list[PipelineTask] = list(spec.input_data)
        for stage_spec in spec.stages:
            stage = stage_spec.stage
            t0 = time.monotonic()
            workers = self._stage_workers(stage_spec)
            # the TPU belongs to one process: accelerator stages (and
            # explicit single-worker stages) run in-process
            if stage.resources.tpus > 0 or workers == 1:
                tasks = self._run_inline(stage, stage_spec, tasks)
            else:
                tasks = self._run_pool(stage, stage_spec, tasks, workers)
            stage_s = time.monotonic() - t0
            self.stage_times[stage.name] = self.stage_times.get(stage.name, 0.0) + stage_s
            logger.info(
                "map stage %s: -> %d tasks in %.2fs (%s)",
                stage.name, len(tasks), stage_s,
                "inline" if stage.resources.tpus > 0 or workers == 1 else f"{workers} procs",
            )
        return tasks if spec.config.return_last_stage_outputs else None

    def _run_inline(self, stage, stage_spec, tasks):
        from cosmos_curate_tpu.core.pipeline import PipelineConfig

        sub = SequentialRunner(raise_on_error=self.raise_on_error)
        spec_one = PipelineSpec(
            input_data=tasks,
            stages=[stage_spec],
            config=PipelineConfig(return_last_stage_outputs=True),
        )
        return sub.run(spec_one) or []

    def _run_pool(self, stage, stage_spec, tasks, workers):
        import multiprocessing

        bs = max(1, stage.batch_size)
        batches = [tasks[i : i + bs] for i in range(0, len(tasks), bs)]
        if not batches:
            return []
        out: list[PipelineTask] = []
        ctx = multiprocessing.get_context("spawn")
        stage_bytes = pickle.dumps(stage)
        attempts = max(1, stage_spec.num_run_attempts)
        with ProcessPoolExecutor(
            max_workers=min(workers, len(batches)),
            mp_context=ctx,
            initializer=_worker_init,
            initargs=(stage_bytes, stage.name),
        ) as pool:
            pending = {pool.submit(_worker_process, pickle.dumps(b)): (b, 1) for b in batches}
            while pending:
                from concurrent.futures import FIRST_COMPLETED, wait

                done, _ = wait(pending, return_when=FIRST_COMPLETED)
                for fut in done:
                    batch, attempt = pending.pop(fut)
                    try:
                        result = pickle.loads(fut.result())
                    except Exception:
                        if attempt < attempts:
                            pending[pool.submit(_worker_process, pickle.dumps(batch))] = (
                                batch, attempt + 1,
                            )
                            continue
                        if self.raise_on_error:
                            raise
                        logger.exception(
                            "map stage %s: batch failed after %d attempts; dropping",
                            stage.name, attempt,
                        )
                        continue
                    if result:
                        out.extend(result)
        return out
