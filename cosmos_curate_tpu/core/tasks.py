"""Pipeline task base class.

Equivalent surface of the reference's ``PipelineTask``
(cosmos_curate/core/interfaces/stage_interface.py:27-58): tasks carry a
``weight`` used by the scheduler for load-balancing, a ``fraction`` used for
progress accounting when one input fans out into many tasks (dynamic
chunking), and ``get_major_size()`` used by the engine for object-store memory
accounting and backpressure.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, fields, is_dataclass
from typing import Any

import numpy as np


def estimate_major_size(obj: Any) -> int:
    """Best-effort deep size of the *payload* of an object graph, in bytes.

    Counts the dominant buffers (bytes, bytearray, memoryview, numpy arrays,
    strings) reachable from ``obj`` via dataclass fields, dicts, lists, tuples
    and sets. Cycle-safe. Mirrors the BFS accounting the reference does in
    data_model.py:94 (``get_major_size``) so the engine can budget the object
    store without serializing.
    """
    seen: set[int] = set()
    total = 0
    stack = [obj]
    while stack:
        o = stack.pop()
        oid = id(o)
        if oid in seen or o is None:
            continue
        seen.add(oid)
        if isinstance(o, memoryview):
            total += o.nbytes
        elif isinstance(o, (bytes, bytearray)):
            total += len(o)
        elif isinstance(o, np.ndarray):
            total += o.nbytes
        elif isinstance(o, str):
            total += len(o)
        elif isinstance(o, dict):
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple, set, frozenset)):
            stack.extend(o)
        elif is_dataclass(o) and not isinstance(o, type):
            for f in fields(o):
                stack.append(getattr(o, f.name, None))
        elif hasattr(o, "get_major_size") and callable(o.get_major_size) and oid != id(obj):
            # Nested objects that do their own accounting.
            total += int(o.get_major_size())
        elif hasattr(o, "__dict__"):
            stack.extend(vars(o).values())
        else:
            total += sys.getsizeof(o, 0)
    return total


@dataclass
class PipelineTask:
    """Base class for units of work flowing between stages.

    Subclasses are plain dataclasses; everything on them must be picklable
    (numpy arrays and bytes ride a zero-copy path through the object store —
    see engine/object_store.py).
    """

    @property
    def weight(self) -> float:
        """Relative scheduling weight; default 1 per task."""
        return 1.0

    @property
    def fraction(self) -> float:
        """Fraction of an original input this task represents (for progress).

        A stage that re-chunks one task into N emits tasks whose fractions sum
        to the parent's fraction.
        """
        return 1.0

    def get_major_size(self) -> int:
        """Approximate payload size in bytes, for object-store accounting."""
        return estimate_major_size(self)
