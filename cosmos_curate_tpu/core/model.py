"""Model interface: the contract between stages and the models they drive.

Equivalent of the reference's ``ModelInterface``
(cosmos_curate/core/interfaces/model_interface.py:20-54). The engine uses
``model_id_names`` to pre-stage weights on every node before workers start
(model/model_utils.py:139 in the reference); ``setup()`` runs inside the
worker and must leave the model ready for inference (for JAX models: params
loaded on device, forward jitted or ready to jit).
"""

from __future__ import annotations

import abc


class ModelInterface(abc.ABC):
    """Base class for all models driven by pipeline stages."""

    @property
    def env_name(self) -> str:
        """Advisory execution-environment tag (see core.stage docstring)."""
        return "default"

    @property
    @abc.abstractmethod
    def model_id_names(self) -> list[str]:
        """Weight-registry ids this model needs staged locally."""

    @abc.abstractmethod
    def setup(self) -> None:
        """Load weights and build the inference callable (inside a worker)."""
