"""Model interface: the contract between stages and the models they drive.

Equivalent of the reference's ``ModelInterface``
(cosmos_curate/core/interfaces/model_interface.py:20-54). The engine uses
``model_id_names`` to pre-stage weights on every node before workers start
(model/model_utils.py:139 in the reference); ``setup()`` runs inside the
worker and must leave the model ready for inference (for JAX models: params
loaded on device, forward jitted or ready to jit).

Device-dispatch contract: JAX models do NOT block on readback inline
(``np.asarray(jit_fn(...))`` — the sync-readback lint rule rejects it).
``setup()`` constructs a ``models.device_pipeline.DevicePipeline`` around
the jitted apply and inference entry points dispatch through it, so H2D
transfer, device compute, and D2H readback overlap across micro-batches.
Models with a submit/drain surface (the SR family) expose
``submit_window``/``drain_windows`` on top of the same pipeline;
``device_pipeline`` below gives stages and diagnostics uniform access.
"""

from __future__ import annotations

import abc


class ModelInterface(abc.ABC):
    """Base class for all models driven by pipeline stages."""

    @property
    def env_name(self) -> str:
        """Advisory execution-environment tag (see core.stage docstring)."""
        return "default"

    @property
    def device_pipeline(self):
        """The model's DevicePipeline after ``setup()``, else None.

        None also for models whose device work runs elsewhere (the caption
        engine's continuous-batching loop is its own dispatch point)."""
        return getattr(self, "_pipeline", None)

    @property
    def pin_to_single_worker(self) -> bool:
        """Stages driving this model must dispatch from ONE worker thread.

        ``DevicePipeline`` state (the bounded in-flight window, bucket
        reuse, submission-order drain) is deliberately single-threaded —
        concurrent submit/drain from several threads would interleave
        micro-batches and misalign results. The pipelined runner
        (core/pipelined_runner.py) reads this marker and pins model stages
        to a single worker; a model whose dispatch really is thread-safe
        may override to allow fan-out."""
        return True

    @property
    @abc.abstractmethod
    def model_id_names(self) -> list[str]:
        """Weight-registry ids this model needs staged locally."""

    @abc.abstractmethod
    def setup(self) -> None:
        """Load weights and build the inference callable (inside a worker)."""
