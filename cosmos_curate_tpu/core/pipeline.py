"""Pipeline spec, config, and the blocking ``run_pipeline`` entry point.

Equivalent surface of the reference's ``run_pipeline``/``PipelineSpec``/
``PipelineConfig``/``StreamingSpecificSpec``
(cosmos_curate/core/interfaces/pipeline_interface.py:281-329,
runner_interface.py:92-170).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Sequence

from cosmos_curate_tpu.core.stage import Stage, StageSpec, fill_default_lifetimes
from cosmos_curate_tpu.core.tasks import PipelineTask

if TYPE_CHECKING:
    from cosmos_curate_tpu.core.runner import RunnerInterface


class ExecutionMode(enum.Enum):
    """STREAMING keeps every stage's pool live simultaneously (requires the
    summed TPU request to fit the cluster); BATCH runs stage-by-stage,
    letting one stage use the whole cluster at a time
    (pipeline_interface.py:155-164 in the reference)."""

    STREAMING = "streaming"
    BATCH = "batch"


@dataclass
class StreamingSpec:
    """Autoscaler / backpressure tuning for STREAMING mode.

    Defaults mirror the reference's ``StreamingSpecificSpec``
    (runner_interface.py:92-101): 180 s autoscale cadence, per-stage input
    queues bounded at ``max(lower_bound, multiplier × pool size)``.
    """

    autoscale_interval_s: float = 180.0
    speed_estimation_window_s: float = 180.0
    max_queued_multiplier: float = 1.5
    max_queued_lower_bound: int = 16
    # Object-store budget for in-flight payloads, as a fraction of host RAM.
    object_store_fraction: float = 0.3


@dataclass(frozen=True)
class ClusterShape:
    """Declared cluster geometry (totals across hosts) that the pre-flight
    validates specs against — STREAMING feasibility and each TPU stage's
    declared ``MeshSpec`` tiling (analysis/graph_lint.py). ``None`` fields
    are discovered at run time instead of validated."""

    num_cpus: float | None = None
    num_tpu_chips: int | None = None


@dataclass
class PipelineConfig:
    execution_mode: ExecutionMode = ExecutionMode.STREAMING
    streaming: StreamingSpec = field(default_factory=StreamingSpec)
    enable_work_stealing: bool = True
    return_last_stage_outputs: bool = True
    log_verbosity: int = 1
    # Total resources; None = discover from the local host.
    num_cpus: float | None = None
    num_tpu_chips: int | None = None

    @property
    def cluster_shape(self) -> ClusterShape:
        return ClusterShape(num_cpus=self.num_cpus, num_tpu_chips=self.num_tpu_chips)


@dataclass
class PipelineSpec:
    input_data: list[PipelineTask]
    stages: list[StageSpec]
    config: PipelineConfig = field(default_factory=PipelineConfig)


def _normalize_stages(
    stages: Sequence[Stage | StageSpec],
) -> list[StageSpec]:
    out: list[StageSpec] = []
    for s in stages:
        spec = s if isinstance(s, StageSpec) else StageSpec(stage=s)
        out.append(fill_default_lifetimes(spec))
    return out


def run_pipeline(
    input_tasks: Sequence[PipelineTask],
    stages: Sequence[Stage | StageSpec],
    config: PipelineConfig | None = None,
    runner: "RunnerInterface | None" = None,
    *,
    skip_validation: bool = False,
) -> list[PipelineTask] | None:
    """Run ``input_tasks`` through ``stages``; blocks until done.

    ``runner`` is the testability seam (the reference's single most important
    one, SURVEY.md §4): tests inject a ``SequentialRunner`` to execute every
    stage in-process with zero infrastructure; production uses the streaming
    engine runner.

    The spec is validated before any worker spawns (stage-to-stage task-type
    flow, duplicate names, STREAMING resource feasibility — see
    cosmos_curate_tpu/analysis/graph_lint.py); a mis-wired pipeline raises
    ``PipelineValidationError`` immediately instead of failing deep into the
    run. ``skip_validation=True`` bypasses the pre-flight.
    """
    from cosmos_curate_tpu.core.runner import default_runner

    config = config or PipelineConfig()
    spec = PipelineSpec(
        input_data=list(input_tasks),
        stages=_normalize_stages(stages),
        config=config,
    )
    if not skip_validation:
        from cosmos_curate_tpu.analysis.graph_lint import validate_pipeline_spec

        validate_pipeline_spec(spec)
    active = runner if runner is not None else default_runner()
    return active.run(spec)
