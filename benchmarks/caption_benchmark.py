"""Caption-engine throughput benchmark: output tokens/s + decode MFU.

Equivalent capability of the reference's speed-of-light caption accounting
(docs/curator/design/SPEED_OF_LIGHT.md:22-81 — output tok/s is THE caption
metric; efficiency = achieved/peak). Runs the continuous-batching engine on
a fixed multimodal workload and prints one JSON line:

  {"metric": "caption_output_tokens_per_sec", "value": N, "unit": "tok/s",
   "decode_mfu": M, "prefill_s": P, ...}

Usage:
  python -m benchmarks.caption_benchmark [--requests 16] [--max-new 64]
                                         [--config base|tiny] [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--config", choices=("base", "tiny"), default="base")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--frames", type=int, default=4)
    ap.add_argument(
        "--uniform",
        action="store_true",
        help="all-equal prompt lengths (default is a mixed-length workload: "
        "1/3 of requests carry a long transcript-style prompt, exercising "
        "chunked prefill + the short/long KV lanes)",
    )
    args = ap.parse_args()

    import numpy as np

    from cosmos_curate_tpu.models.flops import chip_peak_flops, mfu, vlm_decode_flops_per_token
    from cosmos_curate_tpu.models.prompts import get_caption_prompt
    from cosmos_curate_tpu.models.vlm import (
        CaptionEngine,
        CaptionRequest,
        SamplingConfig,
        VLM_BASE,
        VLM_TINY_TEST,
    )

    cfg = VLM_BASE if args.config == "base" else VLM_TINY_TEST
    # mixed-length workload gets short/long KV lanes so KV memory tracks
    # actual lengths (half the slots short, half worst-case)
    lanes = None
    if not args.uniform:
        short = min(max(256, cfg.max_seq // 4), cfg.max_seq // 2)
        lanes = ((short, max(2, args.batch // 2)), (cfg.max_seq, max(2, args.batch // 2)))
    engine = CaptionEngine(cfg, max_batch=args.batch, kv_lanes=lanes)
    engine.setup()
    tok = engine.tokenizer
    prompt_ids = tok.encode(get_caption_prompt("default"))
    long_ids = tok.encode(
        get_caption_prompt("default")
        + " transcript: " + "the camera pans across the scene. " * 40
    )
    rng = np.random.default_rng(0)
    size = cfg.vision.image_size if cfg.vision_variant == "vit" else cfg.qwen_vision.image_size

    def make_request(rid: str, i: int = 0) -> CaptionRequest:
        ids = long_ids if (not args.uniform and i % 3 == 2) else prompt_ids
        return CaptionRequest(
            request_id=rid,
            prompt_ids=list(ids),
            frames=rng.integers(0, 255, (args.frames, size, size, 3), dtype=np.uint8),
            sampling=SamplingConfig(max_new_tokens=args.max_new),
        )

    # warmup: compile prefill buckets + decode programs (both lanes'
    # shapes) outside the window
    engine.add_request(make_request("warmup"))
    if not args.uniform:
        engine.add_request(make_request("warmup-long", 2))
    engine.run_until_complete()
    engine.reset_stats()

    t0 = time.monotonic()
    for i in range(args.requests):
        engine.add_request(make_request(f"r{i}", i))
    results = engine.run_until_complete()
    elapsed = time.monotonic() - t0

    out_tokens = sum(r.num_output_tokens for r in results)
    decode_tok_s = engine.tokens_per_second
    end_to_end_tok_s = out_tokens / elapsed if elapsed > 0 else 0.0
    decode_flops = vlm_decode_flops_per_token(cfg)

    import jax

    record = {
        "metric": "caption_output_tokens_per_sec",
        "value": round(end_to_end_tok_s, 2),
        "unit": "tok/s",
        "decode_tokens_per_sec": round(decode_tok_s, 2),
        "decode_mfu": round(mfu(decode_flops * engine.decode_tokens, engine.decode_time_s), 5)
        if engine.decode_time_s > 0
        else 0.0,
        "requests": len(results),
        "output_tokens": out_tokens,
        "elapsed_s": round(elapsed, 2),
        # dead-work measure: fraction of executed decode rows that produced
        # a token (static slot batches; VERDICT r2 weak #5)
        "decode_slot_utilization": round(engine.decode_slot_utilization, 3),
        "kv_bytes": engine.kv_bytes(),
        "peak_flops": chip_peak_flops(),
        "backend": jax.devices()[0].platform,
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
