"""Caption-engine throughput benchmark: output tokens/s, decode MFU, and
pipeline efficiency.

Equivalent capability of the reference's speed-of-light caption accounting
(docs/curator/design/SPEED_OF_LIGHT.md:22-81 — output tok/s is THE caption
metric; efficiency = achieved/peak, and :67-81 — PIPELINE efficiency =
in-pipeline tok/s ÷ standalone engine tok/s on identical requests). Runs
the continuous-batching engine on a fixed multimodal workload, then runs
the SAME windows through the CaptionStage machinery sharing the SAME
engine, and prints one JSON line:

  {"metric": "caption_output_tokens_per_sec", "value": N, "unit": "tok/s",
   "decode_mfu": M, "caption_pipeline_efficiency": E, ...}

Usage:
  python -m benchmarks.caption_benchmark [--requests 16] [--max-new 64]
                                         [--config base|tiny] [--batch 8]
                                         [--no-pipeline]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--config", choices=("base", "tiny"), default="base")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--frames", type=int, default=4)
    ap.add_argument(
        "--uniform",
        action="store_true",
        help="all-equal prompt lengths (default is a mixed-length workload: "
        "1/3 of requests carry a long transcript-style prompt, exercising "
        "chunked prefill + the short/long KV lanes)",
    )
    ap.add_argument(
        "--no-pipeline",
        action="store_true",
        help="skip the pipeline-efficiency measurement",
    )
    ap.add_argument(
        "--no-cross-job",
        action="store_true",
        help="skip the cross-job continuous-batching measurement (two "
        "owners submitting concurrently into the shared engine)",
    )
    ap.add_argument(
        "--paged-attention",
        choices=("auto", "kernel", "gather"),
        default="auto",
        help="attention program family: paged (kernel reads the KV pool "
        "through the block table) vs the legacy gather-view programs",
    )
    args = ap.parse_args()

    import numpy as np

    from cosmos_curate_tpu.models.flops import chip_peak_flops, mfu, vlm_decode_flops_per_token
    from cosmos_curate_tpu.models.prompts import get_caption_prompt
    from cosmos_curate_tpu.models.vlm import (
        CaptionEngine,
        CaptionRequest,
        SamplingConfig,
        VLM_BASE,
        VLM_TINY_TEST,
    )

    cfg = VLM_BASE if args.config == "base" else VLM_TINY_TEST
    # mixed-length workload gets short/long KV lanes so KV memory tracks
    # actual lengths (half the slots short, half worst-case)
    lanes = None
    if not args.uniform:
        short = min(max(256, cfg.max_seq // 4), cfg.max_seq // 2)
        lanes = ((short, max(2, args.batch // 2)), (cfg.max_seq, max(2, args.batch // 2)))
    # async_prep mirrors the production stage: vision encode of request N+1
    # overlaps decode of request N
    engine = CaptionEngine(
        cfg,
        max_batch=args.batch,
        kv_lanes=lanes,
        async_prep=True,
        paged_attention=args.paged_attention,
    )
    engine.setup()
    tok = engine.tokenizer
    prompt_ids = tok.encode(get_caption_prompt("default"))
    long_ids = tok.encode(
        get_caption_prompt("default")
        + " transcript: " + "the camera pans across the scene. " * 40
    )
    rng = np.random.default_rng(0)
    size = cfg.vision.image_size if cfg.vision_variant == "vit" else cfg.qwen_vision.image_size

    def make_request(rid: str, i: int = 0) -> CaptionRequest:
        # instruction text rides as prefix_ids (before the vision block) —
        # the production layout (captioning._CaptionVLM.encode_prompt), so
        # the shared-prefix KV cache applies: each unique prompt prefills
        # its text once per run instead of once per request
        ids = long_ids if (not args.uniform and i % 3 == 2) else prompt_ids
        return CaptionRequest(
            request_id=rid,
            prefix_ids=list(ids),
            prompt_ids=[],
            frames=rng.integers(0, 255, (args.frames, size, size, 3), dtype=np.uint8),
            sampling=SamplingConfig(max_new_tokens=args.max_new),
        )

    # warmup with the FULL workload mix: prefill buckets (incl. the grouped
    # n_pad shapes batched admission produces), decode programs for both
    # lanes, and the shared-prefix KV builds all compile outside the window
    for i in range(args.requests):
        engine.add_request(make_request(f"warmup-{i}", i))
    engine.run_until_complete()
    engine.reset_stats()

    t0 = time.monotonic()
    for i in range(args.requests):
        engine.add_request(make_request(f"r{i}", i))
    results = engine.run_until_complete()
    elapsed = time.monotonic() - t0

    out_tokens = sum(r.num_output_tokens for r in results)
    decode_tok_s = engine.tokens_per_second
    end_to_end_tok_s = out_tokens / elapsed if elapsed > 0 else 0.0
    decode_flops = vlm_decode_flops_per_token(cfg)

    import jax

    record = {
        "metric": "caption_output_tokens_per_sec",
        "value": round(end_to_end_tok_s, 2),
        "unit": "tok/s",
        "decode_tokens_per_sec": round(decode_tok_s, 2),
        "decode_mfu": round(mfu(decode_flops * engine.decode_tokens, engine.decode_time_s), 5)
        if engine.decode_time_s > 0
        else 0.0,
        "requests": len(results),
        "output_tokens": out_tokens,
        "elapsed_s": round(elapsed, 2),
        # dead-work measure: fraction of executed decode rows that produced
        # a token (static slot batches; VERDICT r2 weak #5)
        "decode_slot_utilization": round(engine.decode_slot_utilization, 3),
        "kv_bytes": engine.kv_bytes(),
        # paged-KV accounting: bytes actually reserved per admitted request
        # (ceil(len/block_size) blocks) vs what the slot-row engine's
        # worst-case lane row cost for the SAME admissions — the paging
        # win; prefix blocks are REFERENCED (prefix_block_refs > 0) with
        # zero whole-prefix device copies (prefix_copy_dispatches == 0 is
        # structural; copy-on-write tail duplications ride kv_cow_copies)
        "kv_block_size": engine.block_size,
        # requested divisor BEFORE the lane-length gcd fallback — when the
        # two differ, this row is not block-size-comparable to rows that
        # asked for the same size over different lanes
        "kv_block_size_requested": engine.block_size_requested,
        # paged-attention path accounting: which program family served the
        # run, decode steps that read the pool through the block table, and
        # the gathered-view bytes those steps never materialized
        "paged_attention": engine.paged_attention,
        "paged_kernel_steps": engine.paged_kernel_steps,
        "kv_gather_bytes_avoided": engine.kv_gather_bytes_avoided,
        "decode_attention_s": round(engine.decode_attention_s, 3),
        "kv_blocks_total": engine.kv_blocks_total,
        "kv_blocks_peak": engine.kv_blocks_used_peak,
        "kv_bytes_per_request": round(engine.kv_bytes_reserved_per_request, 1),
        "kv_bytes_per_request_worst_case": round(
            engine.kv_bytes_worstcase_per_request, 1
        ),
        "prefix_block_refs": engine.prefix_block_refs,
        "prefix_copy_dispatches": engine.prefix_copy_dispatches,
        "kv_cow_copies": engine.kv_cow_copies,
        # shared-prefix KV cache traffic for the measured pass: hits should
        # be ~requests (cache warm from warmup), and prefill_tokens should
        # be down by prefix_len x requests vs an uncached run
        "prefill_tokens": engine.prefill_tokens,
        "prefix_cache_hits": engine.prefix_cache_hits,
        "prefix_cache_misses": engine.prefix_cache_misses,
        "prefix_tokens_saved": engine.prefix_tokens_saved,
        # per-phase seconds for the measured pass; idle = elapsed minus the
        # device phases (prefill + decode) — prep hiding behind decode
        # shows up as prep_s > 0 with idle_s ~ 0
        "caption_phases": {
            **{k: round(v, 3) for k, v in engine.phase_seconds.items()},
            "idle_s": round(
                max(
                    0.0,
                    elapsed
                    - engine.phase_seconds["prefill_s"]
                    - engine.phase_seconds["decode_s"],
                ),
                3,
            ),
        },
        "peak_flops": chip_peak_flops(),
        "backend": jax.devices()[0].platform,
    }
    if not args.no_cross_job:
        record["cross_job"] = _cross_job_interleave(engine, make_request, args)
    if not args.no_pipeline:
        record.update(_pipeline_efficiency(cfg, engine, args))
    print(json.dumps(record))
    return 0


def _cross_job_interleave(engine, make_request, args) -> dict:
    """Cross-job continuous batching: two owners (standing in for two
    concurrent pipelines sharing one SharedCaptionEngine) submit and drive
    concurrently; healthy interleave shows decode steps whose active slots
    span BOTH owners and per-owner token accounting, instead of the jobs
    serializing."""
    import threading

    n = max(2, args.requests // 2)
    steps0 = engine.interleaved_decode_steps
    tokens0 = dict(engine.owner_decode_tokens)
    results: dict = {}

    # submit BOTH owners' requests before any drive starts: fair admission
    # then deterministically seats both owners in the first decode window
    # (thread start skew must not decide whether the interleave happens —
    # the static-checks smoke asserts on it)
    t0 = time.monotonic()
    for tag in ("job0", "job1"):
        for i in range(n):
            req = make_request(f"{tag}-{i}", i)
            req.owner = tag
            engine.add_request(req)

    def job(tag: str) -> None:
        results[tag] = engine.run_until_complete(owner=tag)

    threads = [threading.Thread(target=job, args=(f"job{j}",)) for j in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    owner_tokens = {
        o: v - tokens0.get(o, 0)
        for o, v in engine.owner_decode_tokens.items()
        if o in ("job0", "job1")
    }
    out_tokens = sum(r.num_output_tokens for rs in results.values() for r in rs)
    return {
        "owners": 2,
        "requests_per_owner": n,
        "interleaved_steps": engine.interleaved_decode_steps - steps0,
        "owner_decode_tokens": owner_tokens,
        "tokens_per_sec": round(out_tokens / elapsed, 2) if elapsed > 0 else 0.0,
    }


def _pipeline_efficiency(cfg, engine, args) -> dict:
    """SPEED_OF_LIGHT.md:67-81 — pipeline efficiency: the SAME caption
    windows run (a) straight through the engine and (b) through the
    CaptionStage machinery (windowing structures, per-window request
    construction, result mapping) sharing the same engine; the ratio
    isolates the pipeline wrapper's cost from raw decode throughput."""
    import time as _time

    import numpy as np

    from cosmos_curate_tpu.core.pipeline import run_pipeline
    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.data.model import (
        Clip,
        FrameExtractionSignature,
        SplitPipeTask,
        Video,
        VideoMetadata,
    )
    from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig
    from cosmos_curate_tpu.pipelines.video.stages import captioning as cap_mod

    size = (
        cfg.vision.image_size if cfg.vision_variant == "vit" else cfg.qwen_vision.image_size
    )
    rng = np.random.default_rng(1)
    sig = FrameExtractionSignature("fps", 4.0)
    tasks = []
    for i in range(args.requests):
        clip = Clip(span=(0.0, 2.0))
        # pre-extracted frames: the efficiency ratio isolates the caption
        # path, not decode (which has its own clips/s benchmark)
        clip.extracted_frames[sig.key()] = rng.integers(
            0, 255, (8, size, size, 3), dtype=np.uint8
        )
        video = Video(
            path=f"bench-{i}.mp4",
            metadata=VideoMetadata(
                width=size, height=size, fps=12.0, num_frames=24, duration_s=2.0
            ),
            clips=[clip],
        )
        tasks.append(SplitPipeTask(video=video))

    prep = cap_mod.CaptionPrepStage(frames_per_window=args.frames, extraction=sig)
    prepped = run_pipeline(tasks, [prep], runner=SequentialRunner())

    # (a) standalone: identical prompts + frames, straight into the engine
    stage = cap_mod.CaptionStage(
        cfg=cfg, max_batch=args.batch, max_new_tokens=args.max_new
    )
    # the stage must adopt the ALREADY-BUILT engine (a second engine would
    # double weight memory on chip): seed the process-level registry under
    # the key _CaptionVLM.setup resolves
    from cosmos_curate_tpu.models.vlm import SharedCaptionEngine

    SharedCaptionEngine.adopt(
        engine, cfg=cfg, model_id=cap_mod._CaptionVLM.MODEL_ID
    )
    stage.model.setup()
    windows = [
        (f"{t_i}-{w_i}", win)
        for t_i, task in enumerate(prepped)
        for clip in task.video.clips
        for w_i, win in enumerate(clip.windows)
        if win.frames is not None
    ]
    if not windows:
        return {}

    def submit_all(tag: str) -> None:
        for rid, win in windows:
            prefix_ids, prompt_ids = stage.model.encode_prompt(
                stage.prompt_text, has_vision=True
            )
            engine.add_request(
                CaptionRequest(
                    request_id=f"{tag}{rid}",
                    prefix_ids=prefix_ids,
                    prompt_ids=prompt_ids,
                    frames=win.frames,
                    frame_fps=win.frame_fps,
                    sampling=SamplingConfig(max_new_tokens=stage.max_new_tokens),
                )
            )

    # warmup with the FULL workload: prefill-group and decode shapes for
    # this exact request mix must compile OUTSIDE both measured passes, or
    # whichever pass runs first eats the XLA compile and the ratio inverts
    submit_all("warm-")
    engine.run_until_complete()
    engine.reset_stats()  # decode_tokens is cumulative: zero it for (a)
    t0 = _time.monotonic()
    submit_all("")
    engine.run_until_complete()
    standalone_s = _time.monotonic() - t0
    # SAME counter basis as the pipeline pass (decode_tokens excludes the
    # prefill-sampled first token; num_output_tokens includes it — mixing
    # the two biases the ratio low by ~1 token/request)
    standalone_tokens = engine.decode_tokens
    standalone_tok_s = standalone_tokens / standalone_s if standalone_s > 0 else 0.0

    # (b) in-pipeline: the same windows through the CaptionStage
    engine.reset_stats()
    t0 = _time.monotonic()
    run_pipeline(prepped, [stage], runner=SequentialRunner())
    pipeline_s = _time.monotonic() - t0
    pipeline_tokens = engine.decode_tokens
    pipeline_tok_s = pipeline_tokens / pipeline_s if pipeline_s > 0 else 0.0

    # decompose the pipeline pass: where the wall went (prep hidden behind
    # decode shows prep_s > 0 with idle_s ~ 0) and what the prefix cache
    # saved (reference SPEED_OF_LIGHT.md:67-81 wants the gap ATTRIBUTED,
    # not just measured)
    phases = engine.phase_seconds
    pipeline_idle_s = max(0.0, pipeline_s - phases["prefill_s"] - phases["decode_s"])
    return {
        "standalone_tokens_per_sec": round(standalone_tok_s, 2),
        "pipeline_tokens_per_sec": round(pipeline_tok_s, 2),
        "caption_pipeline_efficiency": round(
            pipeline_tok_s / standalone_tok_s, 3
        )
        if standalone_tok_s > 0
        else 0.0,
        "pipeline_phases": {
            **{k: round(v, 3) for k, v in phases.items()},
            "idle_s": round(pipeline_idle_s, 3),
            "wall_s": round(pipeline_s, 3),
        },
        "pipeline_prefill_tokens": engine.prefill_tokens,
        "pipeline_prefix_cache_hits": engine.prefix_cache_hits,
        "pipeline_prefix_tokens_saved": engine.prefix_tokens_saved,
        "pipeline_vision_encodes": engine.vision_encodes,
    }


if __name__ == "__main__":
    sys.exit(main())
