"""Motion-filter threshold calibration harness.

The reference's thresholds (motion_filter_stages.py:40-126) are on its
codec-motion-vector scale; our estimator is frame differences, so defaults
are calibrated here instead: synthesize static / textured-static / panning /
slow-panning / jittery clips, run them through a REAL encode-decode
roundtrip (codec noise included), score with the stage's jitted kernel, and
report the class separation plus a suggested threshold.

Usage: python -m benchmarks.motion_calibration [--size 240x320] [--frames 48]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import numpy as np


def make_fixture(kind: str, seed: int, *, h: int, w: int, t: int) -> np.ndarray:
    r = np.random.default_rng(seed)
    base = r.integers(30, 220, 3)
    tex = r.integers(0, 255, (h * 2, w * 2, 3)).astype(np.uint8)
    frames = np.zeros((t, h, w, 3), np.uint8)
    for i in range(t):
        if kind == "static":
            frames[i] = base
        elif kind == "static_tex":
            frames[i] = tex[:h, :w]
        elif kind == "pan":
            off = int(i * 1.5)
            frames[i] = tex[10 : 10 + h, off : off + w]
        elif kind == "slow_pan":
            off = int(i * 0.5)
            frames[i] = tex[10 : 10 + h, off : off + w]
        elif kind == "jitter":
            dy, dx = r.integers(-2, 3, 2)
            frames[i] = tex[20 + dy : 20 + dy + h, 20 + dx : 20 + dx + w]
        elif kind == "corner_box":
            frames[i] = base
            x = 10 + int(i * 1.2)
            frames[i, 10:50, x : x + 40] = 255 - base
        else:
            raise ValueError(kind)
    return frames


STATIC_KINDS = ("static", "static_tex")
MOVING_KINDS = ("pan", "slow_pan", "jitter", "corner_box")


def score_fixture(frames: np.ndarray) -> tuple[float, float]:
    from cosmos_curate_tpu.models.batching import pad_batch
    from cosmos_curate_tpu.pipelines.video.stages.motion_filter import _motion_scores
    from cosmos_curate_tpu.video.decode import extract_frames_at_fps
    from cosmos_curate_tpu.video.encode import encode_frames

    data = encode_frames(frames, 24.0)
    dec = extract_frames_at_fps(data, target_fps=4.0, resize_hw=(128, 128))
    padded, n = pad_batch(dec)
    g, p = _motion_scores(padded, n)
    return float(g), float(p)


def score_fixture_mv(frames: np.ndarray) -> tuple[float, float]:
    """Codec-MV estimator scores (video/motion_vectors.py) through the same
    encode roundtrip; (-1, -1) when no MVs are available."""
    from cosmos_curate_tpu.video.encode import encode_frames
    from cosmos_curate_tpu.video.motion_vectors import (
        extract_mv_field,
        mv_motion_scores,
    )

    data = encode_frames(frames, 24.0)
    mv = extract_mv_field(data)
    scores = mv_motion_scores(mv) if mv is not None else None
    return scores if scores is not None else (-1.0, -1.0)


def calibrate(
    *, h: int = 240, w: int = 320, t: int = 48, seeds: int = 3, mv: bool = False
) -> dict:
    scorer = score_fixture_mv if mv else score_fixture
    per_kind: dict[str, list[float]] = {}
    for kind in STATIC_KINDS + MOVING_KINDS:
        per_kind[kind] = [
            scorer(make_fixture(kind, s, h=h, w=w, t=t))[0] for s in range(seeds)
        ]
        if mv and any(v < 0 for v in per_kind[kind]):
            # the sentinel must not flow into the statistics: a garbage
            # "calibration" with no error is worse than failing
            raise RuntimeError(
                f"codec-MV scoring unavailable for {kind!r} fixtures "
                "(native binding or decoder missing); cannot calibrate --mv"
            )
    static_max = max(v for k in STATIC_KINDS for v in per_kind[k])
    moving_min = min(v for k in MOVING_KINDS for v in per_kind[k])
    # geometric-style midpoint biased low: false-drops of real motion are
    # worse for curation than keeping a borderline-static clip
    suggested = max(1e-4, (static_max + moving_min) / 10.0)
    return {
        "per_kind_global": {k: [round(v, 6) for v in vs] for k, vs in per_kind.items()},
        "static_max": round(static_max, 6),
        "moving_min": round(moving_min, 6),
        "separation": round(moving_min - static_max, 6),
        "suggested_global_threshold": round(suggested, 6),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="240x320")
    ap.add_argument("--frames", type=int, default=48)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument(
        "--mv", action="store_true", help="calibrate the codec-MV estimator"
    )
    a = ap.parse_args()
    h, w = (int(x) for x in a.size.split("x"))
    print(json.dumps(calibrate(h=h, w=w, t=a.frames, seeds=a.seeds, mv=a.mv), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
