"""Canonical split-pipeline benchmark harness.

Equivalent capability of the reference's benchmark harness
(benchmarks/split_pipeline/nvcf_split_benchmark.py + benchmarks/summary.py in
/root/reference): run the canonical split configuration (shot detection,
motion score-only, embeddings — invoke.json's shape) over a corpus, retry
transient failures, and report the headline ``video_hours_per_day_per_chip``
plus the summary-count invariants the reference's tests check.

Usage:
  python -m benchmarks.split_benchmark --input-path DIR [--output-path DIR]
  python -m benchmarks.split_benchmark --synthetic 16   # generate corpus
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def make_synthetic_corpus(root: Path, n: int, *, seconds: float = 8.0) -> Path:
    import cv2
    import numpy as np

    vids = root / "videos"
    vids.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(0)
    fps, w, h = 24.0, 320, 240
    for i in range(n):
        writer = cv2.VideoWriter(
            str(vids / f"bench_{i:04d}.mp4"), cv2.VideoWriter_fourcc(*"mp4v"), fps, (w, h)
        )
        scene_len = int(fps * seconds / 2)
        for s in range(2):
            base = rng.integers(0, 255, 3)
            for f in range(scene_len):
                frame = np.full((h, w, 3), base, np.uint8)
                x = (f * 5 + i * 17) % (w - 40)
                frame[80:140, x : x + 40] = 255 - base
                writer.write(frame)
        writer.release()
    return vids


def run_benchmark(args: argparse.Namespace) -> dict:
    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.pipelines.video.split import SplitPipelineArgs, run_split
    from cosmos_curate_tpu.utils.retry import retry

    out_root = Path(args.output_path or tempfile.mkdtemp(prefix="curate_bench_"))
    if args.synthetic:
        input_path = str(make_synthetic_corpus(out_root, args.synthetic))
    else:
        input_path = args.input_path
    pargs = SplitPipelineArgs(
        input_path=input_path,
        output_path=str(out_root / "out"),
        limit=args.limit,
        splitting_algorithm=args.splitting_algorithm,
        motion_filter="score-only" if args.motion else "disable",
        embedding_model=args.embedding_model,
        extract_fps=(2.0,),
    )

    @retry(attempts=args.attempts, backoff_s=2.0)
    def attempt():
        return run_split(pargs, runner=SequentialRunner() if args.sequential else None)

    t0 = time.monotonic()
    summary = attempt()
    wall = time.monotonic() - t0
    # summary-count invariants (reference test_nvcf_split_benchmark.py)
    assert summary["num_clips"] >= summary["num_transcoded"] >= 0
    assert summary["num_with_embeddings"] <= summary["num_clips"]
    result = {
        "video_hours_per_day_per_chip": summary["video_hours_per_day_per_chip"],
        "clips_per_sec": summary["num_clips"] / wall if wall else 0.0,
        "wall_s": wall,
        **{k: summary[k] for k in ("num_videos", "num_clips", "num_transcoded", "num_with_embeddings", "num_errors")},
    }
    print(json.dumps(result, indent=2))
    return result


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--input-path", default="")
    p.add_argument("--output-path", default="")
    p.add_argument("--synthetic", type=int, default=0, help="generate N synthetic videos")
    p.add_argument("--limit", type=int, default=0)
    p.add_argument("--splitting-algorithm", default="fixed-stride")
    p.add_argument("--motion", action="store_true")
    p.add_argument("--embedding-model", default="video")
    p.add_argument("--attempts", type=int, default=3)
    p.add_argument("--sequential", action="store_true")
    args = p.parse_args()
    if not args.input_path and not args.synthetic:
        p.error("--input-path or --synthetic required")
    run_benchmark(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
