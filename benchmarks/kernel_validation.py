"""On-chip validation of the Pallas kernels (decode + prefill attention).

The CPU test suite exercises both kernels in interpreter mode; Mosaic
compilation on a REAL chip is a separate risk (layout/tiling constraints
the interpreter does not model). This script compiles both kernels
non-interpreted, checks them against the XLA reference path, and times
them — meant for the first live-TPU window (the training watcher runs it)
and prints one JSON line per kernel:

  {"kernel": "decode_attention", "ok": true, "max_err": 1e-3,
   "pallas_ms": ..., "xla_ms": ..., "speedup": ...}

Exit code 0 iff every kernel matches.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _bench(fn, *args, iters: int = 20) -> float:
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.monotonic()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / iters * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    platform = jax.devices()[0].platform
    # the POINT is Mosaic compilation on a real chip; off-TPU the script
    # still runs (interpreter) so the harness itself is testable anywhere
    interp = platform != "tpu"
    rng = np.random.default_rng(0)
    failures = 0

    # -- decode kernel: one token vs a long cache -----------------------
    from cosmos_curate_tpu.ops.decode_attention import decode_attention

    b, hk, g, d, s = 8, 2, 6, 128, 4096
    q = jnp.asarray(rng.normal(size=(b, hk, g, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.bfloat16)
    kv_len = jnp.asarray(rng.integers(512, s, size=(b,)), jnp.int32)

    # Mirrors DecoderLayer's XLA mask semantics (also asserted by
    # tests/ops/test_prefill_attention.py::_reference) — any change to the
    # kernels' masking must update all three in lockstep.
    def xla_decode(q, k, v, kv_len):
        logits = jnp.einsum(
            "bkgd,bskd->bkgs", q.astype(jnp.float32) * d**-0.5, k.astype(jnp.float32)
        )
        mask = jnp.arange(s)[None, None, None, :] < kv_len[:, None, None, None]
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bkgs,bskd->bkgd", p, v.astype(jnp.float32))

    try:
        got = decode_attention(q, k, v, kv_len, interpret=interp)
        want = xla_decode(q, k, v, kv_len)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
        ok = err < 2e-2  # bf16 inputs
        rec = {"kernel": "decode_attention", "ok": ok, "max_err": round(err, 5), "platform": platform}
    except Exception as e:  # noqa: BLE001
        rec = {"kernel": "decode_attention", "ok": False, "error": f"{type(e).__name__}: {e}"}
    if rec.get("ok") and platform == "tpu":
        try:  # timing is informational: a bench OOM must not void the PASS
            rec["pallas_ms"] = round(_bench(lambda *a: decode_attention(*a, interpret=False), q, k, v, kv_len), 3)
            rec["xla_ms"] = round(_bench(jax.jit(xla_decode), q, k, v, kv_len), 3)
            rec["speedup"] = round(rec["xla_ms"] / rec["pallas_ms"], 2)
        except Exception as e:  # noqa: BLE001
            rec["bench_error"] = f"{type(e).__name__}: {e}"
    failures += not rec.get("ok")
    print(json.dumps(rec))

    # -- prefill kernel: chunk vs cache ---------------------------------
    from cosmos_curate_tpu.ops.prefill_attention import prefill_attention

    t = 256
    qp = jnp.asarray(rng.normal(size=(b, t, hk, g, d)), jnp.bfloat16)
    write = jnp.asarray(rng.integers(0, s - t, size=(b,)), jnp.int32)
    kvp = write + t

    def xla_prefill(qp, k, v, write, kvp):
        logits = jnp.einsum(
            "btkgd,bskd->bkgts", qp.astype(jnp.float32) * d**-0.5, k.astype(jnp.float32)
        )
        k_pos = jnp.arange(s)[None, None, None, None, :]
        q_seq = write[:, None] + jnp.arange(t)[None, :]
        mask = (k_pos <= q_seq[:, None, None, :, None]) & (
            k_pos < kvp[:, None, None, None, None]
        )
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bkgts,bskd->btkgd", p, v.astype(jnp.float32))

    try:
        got = prefill_attention(qp, k, v, write, kvp, interpret=interp)
        want = xla_prefill(qp, k, v, write, kvp)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
        ok = err < 2e-2
        rec = {"kernel": "prefill_attention", "ok": ok, "max_err": round(err, 5), "platform": platform}
    except Exception as e:  # noqa: BLE001
        rec = {"kernel": "prefill_attention", "ok": False, "error": f"{type(e).__name__}: {e}"}
    if rec.get("ok") and platform == "tpu":
        try:  # timing is informational: a bench OOM must not void the PASS
            rec["pallas_ms"] = round(_bench(lambda *a: prefill_attention(*a, interpret=False), qp, k, v, write, kvp), 3)
            rec["xla_ms"] = round(_bench(jax.jit(xla_prefill), qp, k, v, write, kvp), 3)
            rec["speedup"] = round(rec["xla_ms"] / rec["pallas_ms"], 2)
        except Exception as e:  # noqa: BLE001
            rec["bench_error"] = f"{type(e).__name__}: {e}"
    failures += not rec.get("ok")
    print(json.dumps(rec))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
