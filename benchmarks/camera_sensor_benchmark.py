"""Camera-sensor sampling throughput benchmark.

Equivalent of the reference's camera_sensor_benchmark
(cosmos_curate/core/sensors/scripts/camera_sensor_benchmark.py): frames/s
through ``CameraSensor.sample`` for a given grid rate and window length —
the number that sizes the CPU prep pool feeding TPU stages from sensor
rigs.

Usage: python -m benchmarks.camera_sensor_benchmark [--video PATH]
(synthesizes a fixture video when none is given).
"""

from __future__ import annotations

import argparse
import tempfile
import time
from pathlib import Path

import numpy as np


def synthesize_video(path: str, *, frames: int = 240, fps: float = 24.0) -> None:
    import cv2

    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), fps, (320, 240))
    for i in range(frames):
        frame = np.full((240, 320, 3), (i * 3) % 255, np.uint8)
        frame[50:100, (i * 5) % 280 : (i * 5) % 280 + 40] = 255
        w.write(frame)
    w.release()


def run(video: str, *, rate_hz: float, window_size: int, camera: str = "front") -> dict:
    from cosmos_curate_tpu.sensors.camera_sensor import CameraSensor
    from cosmos_curate_tpu.sensors.sampling import SamplingGrid, SamplingSpec
    from cosmos_curate_tpu.sensors.video_index import camera_frame_refs

    sensor = CameraSensor(camera, camera_frame_refs(camera, video))
    grid = SamplingGrid.from_rate(
        sensor.start_ns,
        sample_rate_hz=rate_hz,
        end_ns=sensor.end_ns,
        window_size=window_size,
    )
    spec = SamplingSpec(grid=grid)
    t0 = time.monotonic()
    frames = 0
    windows = 0
    for batch in sensor.sample(spec):
        frames += len(batch)
        windows += 1
    elapsed = time.monotonic() - t0
    return {
        "windows": windows,
        "frames": frames,
        "elapsed_s": round(elapsed, 3),
        "frames_per_s": round(frames / elapsed, 1) if elapsed > 0 else 0.0,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--video", default="", help="mp4 to sample (synthesized if empty)")
    ap.add_argument("--rate-hz", type=float, default=10.0)
    ap.add_argument("--window-size", type=int, default=64, help="grid samples per window")
    args = ap.parse_args()
    video = args.video
    if not video:
        tmp = tempfile.mkdtemp(prefix="cam_bench_")
        video = str(Path(tmp) / "bench.mp4")
        synthesize_video(video)
    stats = run(video, rate_hz=args.rate_hz, window_size=args.window_size)
    print(
        f"camera sensor: {stats['frames']} frames / {stats['windows']} windows "
        f"in {stats['elapsed_s']}s -> {stats['frames_per_s']} frames/s"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
