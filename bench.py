"""Benchmark harness: split+annotate throughput on this host's TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Mirrors the reference's canonical benchmark shape
(benchmarks/split_pipeline/invoke.json + benchmarks/summary.py in
/root/reference): a fixed corpus of videos through download → fixed-stride
split → transcode → frame-extract → TPU video embedding → write, measuring
end-to-end clips/sec (model compile excluded via warmup; fixture synthesis
excluded). ``vs_baseline`` compares against the recorded value in
BENCH_REF.json (first recorded round = 1.0); the reference repo publishes no
absolute numbers to compare against directly (BASELINE.md).

The split+annotate measurement runs TWICE and the second (warm-cache) pass
is the headline: r03→r05 drifted 0.215→0.182 on identical code paths, which
is warmup noise (first-touch page faults, lazy imports, allocator growth)
that must not be recorded as signal. The cold pass rides along as
``value_cold``. Per-dispatch device timings (models/device_pipeline.py) are
summarized per pipeline; ``dispatch_gap_frac`` < 0.2 on the embed pipeline
is the acceptance bar that H2D/compute actually overlap. With the default
pipelined runner (core/pipelined_runner.py) the record also carries
``pipeline_overlap_frac`` — the fraction of summed host-stage work hidden
behind other stages; > 0 proves decode/transcode ran concurrently with the
embed stage instead of in lockstep.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

NUM_VIDEOS = int(os.environ.get("BENCH_NUM_VIDEOS", "64"))
SCENE_FRAMES = 48
NUM_SCENES = 2  # 4 s per video at 24 fps
STRIDE_S = 1.0
# 720p: flat 320x240 color cards made decode/transcode look free — real
# corpora make the CPU stages earn their allocation (ROADMAP item #2)
FRAME_W, FRAME_H = 1280, 720


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _scene_frames(rng, vid_idx: int, scene_idx: int):
    """One scene's frames: a moving diagonal gradient (global motion a
    codec cannot collapse to a still) over a per-scene noise texture
    (spatial detail that survives resize), plus a tracked high-contrast
    block. Vectorized per frame; deterministic per (video, scene)."""
    import cv2
    import numpy as np

    # per-scene palette and motion parameters from the seeded rng only —
    # regenerating the corpus yields byte-comparable content per video
    c0 = rng.integers(0, 255, 3).astype(np.float32)
    c1 = rng.integers(0, 255, 3).astype(np.float32)
    angle = rng.uniform(0, 2 * np.pi)
    speed = rng.uniform(2.0, 8.0)  # gradient pixels/frame
    # quarter-res noise field upscaled: texture without a 720p RNG bill
    noise = rng.integers(0, 60, (FRAME_H // 4, FRAME_W // 4, 3), dtype=np.uint8)
    noise = cv2.resize(noise, (FRAME_W, FRAME_H), interpolation=cv2.INTER_LINEAR)
    yy, xx = np.mgrid[0:FRAME_H, 0:FRAME_W]
    proj = (np.cos(angle) * xx + np.sin(angle) * yy).astype(np.float32)
    span = float(proj.max() - proj.min()) or 1.0
    bx = int(rng.integers(0, FRAME_W - 160))
    bvx = int(rng.integers(3, 11)) * (1 if scene_idx % 2 == 0 else -1)
    for f in range(SCENE_FRAMES):
        phase = ((proj + f * speed) % span) / span
        frame = (c0[None, None] * (1 - phase[..., None]) + c1[None, None] * phase[..., None])
        frame = np.clip(frame + noise.astype(np.float32) - 30.0, 0, 255).astype(np.uint8)
        x = (bx + f * bvx) % (FRAME_W - 160)
        frame[280:440, x : x + 160] = (255 - c0).astype(np.uint8)
        yield frame


def make_corpus(root: Path) -> Path:
    import cv2
    import numpy as np

    vids = root / "videos"
    vids.mkdir(parents=True, exist_ok=True)
    for i in range(NUM_VIDEOS):
        # one rng per video, seeded by index: adding videos never reshuffles
        # earlier ones, so BENCH rows stay comparable across corpus sizes
        rng = np.random.default_rng(1000 + i)
        path = vids / f"bench_{i}.mp4"
        w = cv2.VideoWriter(
            str(path), cv2.VideoWriter_fourcc(*"mp4v"), 24.0, (FRAME_W, FRAME_H)
        )
        for s in range(NUM_SCENES):
            for frame in _scene_frames(rng, i, s):
                w.write(frame)
        w.release()
    return vids


def ensure_live_backend() -> None:
    """The TPU tunnel can wedge (observed: a dead relay makes ANY jax import
    block for minutes). Probe device init in a subprocess with a timeout —
    retrying with backoff, since the relay recovers on its own schedule — and
    only after every attempt fails re-exec on pure CPU so the bench always
    reports a number (flagged in the JSON) instead of hanging the driver."""

    if os.environ.get("BENCH_BACKEND_CHECKED"):
        return
    attempts = int(os.environ.get("BENCH_TPU_PROBE_ATTEMPTS", "3"))
    from cosmos_curate_tpu.utils.health import accelerator_health_gate

    alive = accelerator_health_gate(
        attempts=attempts, probe_timeout_s=150, backoff_s=45
    )
    if not alive:
        log("bench: TPU backend unavailable; re-executing on CPU (result is NOT a TPU number)")
        env = {**os.environ, "BENCH_BACKEND_CHECKED": "1", "JAX_PLATFORMS": "cpu"}
        env["PYTHONPATH"] = str(REPO)  # drop the axon plugin path
        os.execve(sys.executable, [sys.executable, str(Path(__file__).resolve())], env)
    os.environ["BENCH_BACKEND_CHECKED"] = "1"


def main() -> int:
    ensure_live_backend()
    import numpy as np

    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_BASE, VideoEmbedder
    from cosmos_curate_tpu.pipelines.video.split import SplitPipelineArgs, run_split

    log(f"bench: synthesizing {NUM_VIDEOS} videos")
    tmp = Path(tempfile.mkdtemp(prefix="curate_bench_"))
    vids = make_corpus(tmp)

    # Caption throughput rides along in the same driver artifact (reference
    # SPEED_OF_LIGHT.md:22-52: "output tokens/s is THE metric"). Run it
    # FIRST, before this process initializes JAX: libtpu is single-process,
    # so a child launched after the parent grabs the chip would silently
    # fall back to CPU and poison the number. Subprocess also means an
    # engine failure can't void the clips/s measurement.
    caption: dict = {}
    caption_cfg = "tiny" if os.environ.get("JAX_PLATFORMS") == "cpu" else "base"
    try:
        import subprocess

        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "benchmarks.caption_benchmark",
                "--config",
                caption_cfg,
                "--requests",
                os.environ.get("BENCH_CAPTION_REQUESTS", "8"),
                "--max-new",
                "48",
            ],
            capture_output=True,
            text=True,
            timeout=2400,
            cwd=str(REPO),
            env=dict(os.environ),
        )
        caption = json.loads(proc.stdout.strip().splitlines()[-1])
        log(
            f"bench: caption {caption['value']} tok/s "
            f"(backend={caption.get('backend')}, config={caption_cfg})"
        )
    except Exception as e:  # noqa: BLE001
        log(f"bench: caption benchmark failed ({e}); clips/s still valid")

    # Warm up the embedder compile outside the timed window. The device
    # pipeline dispatches pow2 BUCKET micro-batches (cap-sized chunks plus
    # a pow2 remainder, models/device_pipeline.py:plan_micro_batches), so
    # the compiled-shape universe for any run batch is exactly {pow2 <=
    # cap}: warm all of them, or a remainder bucket compiles inside the
    # timed window and masquerades as throughput loss.
    log("bench: warming up embedder compiles")
    warm = VideoEmbedder(VIDEO_EMBED_BASE)
    warm.setup()
    expected_clips_per_video = int(NUM_SCENES * SCENE_FRAMES / 24.0 / STRIDE_S)
    from cosmos_curate_tpu.models.batching import next_pow2
    from cosmos_curate_tpu.models.device_pipeline import micro_batch_cap

    from cosmos_curate_tpu.pipelines.video.stages.embedding import EMBED_STAGE_TASK_BATCH

    # The embed stage batches across tasks, so the run hits bucket shapes
    # up to min(cap, full task-batch clip count).
    full = next_pow2(expected_clips_per_video * min(EMBED_STAGE_TASK_BATCH, NUM_VIDEOS))
    cap = micro_batch_cap()
    # every pow2 <= min(full, cap): when full > cap the loop's last
    # iteration is cap itself, the only chunk shape used beyond it
    shapes = set()
    b = 1
    while b <= min(full, cap):
        shapes.add(b)
        b *= 2
    for b in sorted(shapes):
        warm.encode_clips(
            np.zeros((b, VIDEO_EMBED_BASE.num_frames, 224, 224, 3), np.uint8)
        )
    del warm

    # The reference's canonical perf config is transnet shot detection +
    # motion + aesthetics + embeddings (benchmarks/split_pipeline/
    # invoke.json:1-45). Run that as the headline whenever trained transnet
    # weights are staged; fall back to fixed-stride (the round-1/2 config)
    # when they are not, and say which one was measured.
    transnet_weights = (REPO / "weights" / "transnetv2-tpu" / "params.msgpack").exists()
    config_name = "transnet+motion+embed" if transnet_weights else "fixed-stride+embed"
    args = SplitPipelineArgs(
        input_path=str(vids),
        output_path=str(tmp / "out"),
        splitting_algorithm="transnetv2" if transnet_weights else "fixed-stride",
        fixed_stride_len_s=STRIDE_S,
        min_clip_len_s=0.5,
        motion_filter="score-only" if transnet_weights else "disable",
        extract_fps=(8.0,),
        extract_resize_hw=(224, 224),
        embedding_model="video",
    )
    # Runner selection (BENCH_RUNNER=sequential|pipelined|engine). The
    # single-host default is the pipelined runner: stage worker-thread
    # pools overlap CPU decode/transcode with the device embed stage
    # (core/pipelined_runner.py) without the engine's worker-spawn
    # overhead, which dominates on small boxes. The streaming engine stays
    # opt-in here — its process pools pay off when decode fans out across
    # many real cores or across hosts.
    choice = os.environ.get("BENCH_RUNNER", "auto")
    cores = os.cpu_count() or 1
    if choice not in ("auto", "sequential", "pipelined", "engine"):
        # a typo must not silently bench the wrong runner under the typo's
        # name in the JSON record (same guard default_runner applies)
        raise SystemExit(f"unknown BENCH_RUNNER={choice!r}")
    if choice == "auto":
        choice = "pipelined"
    use_engine = choice == "engine"

    def make_runner():
        if choice == "engine":
            from cosmos_curate_tpu.engine.runner import StreamingRunner

            return StreamingRunner()
        if choice == "pipelined":
            from cosmos_curate_tpu.core.pipelined_runner import PipelinedRunner

            # production semantics (engine parity): a dropped batch shows up
            # as missing clips in the summary, not as an aborted bench
            return PipelinedRunner(raise_on_error=False)
        return SequentialRunner()

    from cosmos_curate_tpu.observability.stage_timer import (
        DISPATCH_DUMP_DIR_ENV,
        dispatch_summaries,
        load_dumped_summaries,
        reset_dispatch_stats,
        reset_stage_flow,
        stage_flow_summaries,
    )

    # Two passes over identical inputs: pass 1 absorbs residual warmup
    # (page faults, lazy imports, allocator growth — the r03→r05 drift);
    # pass 2 (warm) is the headline. Fresh runner + output dir per pass.
    passes = []
    for label in ("cold", "warm"):
        runner = make_runner()
        # the warm (headline) pass runs traced: spans are a boolean check +
        # buffered NDJSON appends, and the flight recorder turns them into
        # report/run_report.json — the artifact every BENCH row references
        # (`cosmos-curate-tpu report <path>` renders the critical path).
        # A bench-scale run emits a few dozen spans, far below measurement
        # noise, but value/vs_baseline do carry that overhead vs pre-trace
        # baselines and vs the untraced cold pass
        pass_args = dataclasses.replace(
            args, output_path=str(tmp / f"out_{label}"), tracing=label == "warm"
        )
        reset_dispatch_stats()  # per-dispatch stats reflect ONE pass
        reset_stage_flow()  # per-stage queue/busy aggregates too
        # engine mode runs stages in spawned workers: have each worker dump
        # its dispatch aggregates at exit so the warm pass still reports
        os.environ[DISPATCH_DUMP_DIR_ENV] = str(tmp / f"dispatch_{label}")
        log(f"bench: running split+annotate [{label}] ({choice}, {cores} cores)")
        t0 = time.monotonic()
        summary = run_split(pass_args, runner=runner)
        elapsed = time.monotonic() - t0
        passes.append((summary, elapsed, runner))
        log(
            f"bench[{label}]: {summary['num_clips']} clips "
            f"({summary['num_with_embeddings']} embedded) in {elapsed:.1f}s; "
            f"video_hours_per_day_per_chip={summary['video_hours_per_day_per_chip']:.1f}"
        )

    cold_summary, cold_elapsed, _ = passes[0]
    summary, elapsed, runner = passes[1]
    clips = summary["num_clips"]
    embedded = summary["num_with_embeddings"]
    value = clips / elapsed if elapsed > 0 else 0.0
    value_cold = (
        cold_summary["num_clips"] / cold_elapsed if cold_elapsed > 0 else 0.0
    )

    ref_path = REPO / "BENCH_REF.json"
    vs = 1.0
    if ref_path.exists():
        try:
            ref = json.loads(ref_path.read_text())
            if ref.get("value"):
                vs = value / float(ref["value"])
        except Exception as e:
            log(f"bench: unreadable BENCH_REF.json: {e}")
    import jax

    backend = jax.devices()[0].platform
    record = {
        "metric": "clips_per_sec_split_annotate",
        "value": round(value, 3),
        "value_cold": round(value_cold, 3),
        "passes": 2,
        "unit": "clips/s",
        "vs_baseline": round(vs, 3),
        "config": config_name,
        "runner": choice,
    }
    # Stage-overlap signal (pipelined runner): fraction of summed host
    # stage work hidden behind other stages — 0 means lockstep (sequential
    # behavior), >0 means decode/transcode ran while the device embedded.
    overlap = getattr(runner, "overlap_frac", None)
    if overlap is not None:
        record["pipeline_overlap_frac"] = round(overlap, 4)
    flow = stage_flow_summaries()
    if flow:
        log("bench: stage flow (warm pass): " + json.dumps(flow))
    # MFU + embed-stage wall for the warm pass (reference SPEED_OF_LIGHT.md's
    # efficiency method via models/flops.py). Reported on EVERY backend —
    # r02 carried these fields, then they vanished behind a TPU-only gate and
    # the regressions hid with them. A CPU-fallback run is machine-detectable
    # via "backend", and its mfu (computed against the TPU peak) reads as
    # ~0 — flagged, not misleading.
    from cosmos_curate_tpu.models.flops import chip_peak_flops, mfu, video_embed_forward_flops

    embed_s = getattr(runner, "stage_times", {}).get("ClipEmbeddingStage", 0.0)
    if embedded and embed_s > 0:
        flops = embedded * video_embed_forward_flops(VIDEO_EMBED_BASE)
        record["mfu"] = round(mfu(flops, embed_s), 4)
        record["embed_stage_s"] = round(embed_s, 2)
        record["peak_flops"] = chip_peak_flops()
    # Per-dispatch device-pipeline timings (warm pass): gap_frac ≈ 0 means
    # H2D/compute/readback actually overlapped; the acceptance bar is the
    # embed pipeline's dispatch gap < 20% of its device window. In-process
    # stats (sequential runner) merge with any worker dumps (engine mode).
    dispatch = dispatch_summaries()
    for name, agg in load_dumped_summaries(str(tmp / "dispatch_warm")).items():
        dispatch.setdefault(name, agg)
    embed_pipes = {k: v for k, v in dispatch.items() if k.startswith("embed/")}
    if embed_pipes:
        gap = sum(v["gap_s"] for v in embed_pipes.values())
        busy = sum(v["gap_s"] + v["compute_s"] for v in embed_pipes.values())
        record["dispatch_gap_s"] = round(gap, 3)
        record["dispatch_gap_frac"] = round(gap / busy, 4) if busy > 0 else 0.0
        record["dispatches"] = sum(v["dispatches"] for v in embed_pipes.values())
    if dispatch:
        log("bench: per-dispatch timings (warm pass): " + json.dumps(dispatch))
    elif use_engine:
        # no worker dump landed (workers killed before atexit, or a stage
        # never dispatched) — nothing to report this pass
        log("bench: no dispatch stats collected from engine workers")
    if backend != "tpu":
        # degraded run (dead TPU tunnel fallback) must be machine-detectable
        record["backend"] = backend

    # Corpus-index bench (dedup/corpus_index.py): the scenario the index
    # exists for — one run's clips arriving against an already-indexed
    # corpus ≥10x the run's size (BENCH_INDEX_CORPUS_MULT, default 20x —
    # production corpora dwarf one run). Measures fragment-add and query
    # rates plus the headline comparison: incremental dedup via index
    # queries vs a full `semantic_dedup` re-cluster over corpus+run (the
    # acceptance bar is ≥5x). The run's REAL embeddings (warm pass parquet
    # output) are the query batch; the corpus is synthesized AROUND them —
    # half jittered copies of the run's content, half interpolations
    # between run vectors — the continuum structure real curated corpora
    # have (new clips resemble old ones; cluster boundaries are ambiguous,
    # so Lloyd pays its real iteration count instead of snapping in 3).
    try:
        from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex, incremental_dedup
        from cosmos_curate_tpu.dedup.kmeans import semantic_dedup
        from cosmos_curate_tpu.pipelines.video.dedup import load_embeddings

        run_ids, run_vecs, emb_model = load_embeddings(str(tmp / "out_warm"))
        rng = np.random.default_rng(11)
        run_n, dim = run_vecs.shape
        mult = max(10, int(os.environ.get("BENCH_INDEX_CORPUS_MULT", "20")))
        corpus_n = max(mult * run_n, 640)
        half = corpus_n // 2
        similar = (
            np.repeat(run_vecs, (half + run_n - 1) // run_n, 0)[:half]
            + 0.2 * rng.standard_normal((half, dim))
        ).astype(np.float32)
        a = rng.integers(0, run_n, corpus_n - half)
        b = rng.integers(0, run_n, corpus_n - half)
        alpha = rng.uniform(0, 1, (corpus_n - half, 1)).astype(np.float32)
        between = (
            alpha * run_vecs[a] + (1 - alpha) * run_vecs[b]
            + 0.25 * rng.standard_normal((corpus_n - half, dim))
        ).astype(np.float32)
        corpus_vecs = np.concatenate([similar, between])
        corpus_ids = [f"corpus-{i}" for i in range(corpus_n)]
        log(
            f"bench: index bench — {len(run_ids)} run clips vs "
            f"{corpus_n}-vector corpus (dim {run_vecs.shape[1]})"
        )
        index = CorpusIndex.build(
            str(tmp / "bench_index"), corpus_ids, corpus_vecs,
            model=emb_model, metrics_name="bench_index",
        )
        # Both paths warm once outside their timed windows (bench policy:
        # compile excluded via warmup; the persistent compile cache makes
        # production compiles disk hits). Incremental runs on the pre-built
        # index BEFORE the run is added — the production scenario is "new
        # clips arrive against the existing corpus".
        incremental_dedup(index, run_ids, run_vecs, eps=0.07)
        t0 = time.monotonic()
        inc = incremental_dedup(index, run_ids, run_vecs, eps=0.07)
        inc_s = time.monotonic() - t0
        t0 = time.monotonic()
        index.query(run_vecs)
        query_s = time.monotonic() - t0
        t0 = time.monotonic()
        index.add(run_ids, run_vecs)
        add_s = time.monotonic() - t0
        full_input = np.concatenate([corpus_vecs, run_vecs])
        full_ids = corpus_ids + run_ids
        semantic_dedup(full_input, full_ids, eps=0.07)  # warm the Lloyd jits
        t0 = time.monotonic()
        semantic_dedup(full_input, full_ids, eps=0.07)
        full_s = time.monotonic() - t0
        record["index_add_clips_per_sec"] = round(len(run_ids) / add_s, 1) if add_s > 0 else 0.0
        record["index_queries_per_sec"] = round(len(run_ids) / query_s, 1) if query_s > 0 else 0.0
        record["dedup_incremental_s"] = round(inc_s, 3)
        record["dedup_full_recluster_s"] = round(full_s, 3)
        record["dedup_speedup"] = round(full_s / inc_s, 1) if inc_s > 0 else 0.0
        record["dedup_corpus_size"] = corpus_n
        log(
            f"bench: incremental dedup {inc_s:.2f}s vs full re-cluster "
            f"{full_s:.2f}s ({record['dedup_speedup']}x); "
            f"add {record['index_add_clips_per_sec']} clips/s, "
            f"query {record['index_queries_per_sec']} q/s"
        )
        # Search-serving bench (dedup/index_server.py): the /v1/search hot
        # path over the SAME 20x corpus — single-vector requests through the
        # micro-batching server, cold (fresh server, no warmup: every probe
        # faults shards in from storage) vs warm (warmed cache + resident
        # probe union). p50/p99 are the SLO headline; search_qps drives 8
        # concurrent clients so micro-batching across requests is measured,
        # not serial round-trips.
        from concurrent.futures import ThreadPoolExecutor

        from cosmos_curate_tpu.dedup.index_server import IndexServer

        def _latencies(server, qs):
            out = []
            for v in qs:
                t = time.monotonic()
                server.search(v, top_k=5)
                out.append((time.monotonic() - t) * 1e3)
            return out

        n_lat = min(64, len(run_vecs))
        cold_srv = IndexServer(str(tmp / "bench_index"), warmup=False,
                               metrics_name="bench_search_cold")
        try:
            cold = _latencies(cold_srv, run_vecs[:n_lat])
        finally:
            cold_srv.close()
        warm_srv = IndexServer(str(tmp / "bench_index"), metrics_name="bench_search")
        try:
            _latencies(warm_srv, run_vecs[:n_lat])  # fill the probe union
            warm = _latencies(warm_srv, run_vecs[:n_lat])
            qps_n = max(128, 2 * len(run_vecs))
            t0 = time.monotonic()
            with ThreadPoolExecutor(8) as pool:
                list(pool.map(
                    lambda i: warm_srv.search(run_vecs[i % len(run_vecs)], top_k=5),
                    range(qps_n),
                ))
            qps_wall = time.monotonic() - t0
        finally:
            warm_srv.close()
        record["search_qps"] = round(qps_n / qps_wall, 1) if qps_wall > 0 else 0.0
        record["search_latency_p50_ms"] = round(float(np.percentile(warm, 50)), 3)
        record["search_latency_p99_ms"] = round(float(np.percentile(warm, 99)), 3)
        record["search_latency_cold_p50_ms"] = round(float(np.percentile(cold, 50)), 3)
        record["search_latency_cold_p99_ms"] = round(float(np.percentile(cold, 99)), 3)
        log(
            f"bench: search — warm p50 {record['search_latency_p50_ms']}ms "
            f"p99 {record['search_latency_p99_ms']}ms (cold p50 "
            f"{record['search_latency_cold_p50_ms']}ms), "
            f"{record['search_qps']} qps over 8 concurrent clients"
        )
    except Exception as e:  # noqa: BLE001
        log(f"bench: index bench failed ({e}); clips/s still valid")

    # flight-recorder artifact for the warm pass (written by run_split's
    # finalize since the pass ran with tracing): every BENCH row points at
    # the report that explains its number
    from cosmos_curate_tpu.observability.flight_recorder import report_path

    rp = report_path(str(tmp / "out_warm"))
    if Path(rp).exists():
        record["run_report"] = rp
        try:
            rep = json.loads(Path(rp).read_text())
            record["trace_connected"] = bool(rep.get("connected"))
        except Exception as e:  # noqa: BLE001
            log(f"bench: unreadable run report {rp}: {e}")
    else:
        log("bench: warm pass produced no run report")

    # caption_attention micro-section: per-decode-step attention time for
    # the paged programs ("kernel" — on CPU this is the byte-parity XLA
    # reference, same structural win: no gathered working set) vs the
    # legacy gather-view programs, at two context lengths on the tiny
    # config. The counters prove which path ran; the paged step must not
    # lose to gather at the longer context, where the per-step O(context)
    # view copy it deletes is largest.
    try:
        from cosmos_curate_tpu.models.vlm import (
            CaptionEngine,
            CaptionRequest,
            SamplingConfig,
            VLM_TINY_TEST,
        )

        def _decode_step_ms(mode: str, ctx_tokens: int) -> tuple[float, dict]:
            eng = CaptionEngine(
                VLM_TINY_TEST,
                max_batch=1,
                kv_lanes=((VLM_TINY_TEST.max_seq, 1),),
                paged_attention=mode,
                enable_prefix_cache=False,
            )
            eng.setup()

            def drive(rid: str) -> None:
                eng.add_request(
                    CaptionRequest(
                        request_id=rid,
                        prompt_ids=[1 + (i * 7) % 250 for i in range(ctx_tokens)],
                        sampling=SamplingConfig(max_new_tokens=24),
                    )
                )
                eng.run_until_complete()

            drive("warm")  # compiles land outside the measured window
            # best-of-3: a tiny-config decode step is microseconds of real
            # work, so a single scheduler hiccup would swamp the comparison
            best, stats = float("inf"), {}
            for rep in range(3):
                eng.reset_stats()
                drive(f"measure-{rep}")
                stats = eng.stats()
                steps = max(1, stats["decode_tokens"])
                best = min(best, stats["decode_attention_s"] * 1e3 / steps)
            return best, stats

        contexts = (32, 96)
        attn: dict = {"contexts": list(contexts)}
        for mode in ("kernel", "gather"):
            per_ctx = []
            for ctx in contexts:
                step_ms, stats = _decode_step_ms(mode, ctx)
                per_ctx.append(round(step_ms, 4))
            attn[f"{mode}_step_ms"] = per_ctx
            if mode == "kernel":
                attn["decode_attention_s"] = stats["decode_attention_s"]
                attn["kv_gather_bytes_avoided"] = stats["kv_gather_bytes_avoided"]
                attn["paged_kernel_steps"] = stats["paged_kernel_steps"]
        record["caption_attention"] = attn
        log(
            f"bench: caption_attention — kernel {attn['kernel_step_ms']} ms/step "
            f"vs gather {attn['gather_step_ms']} at contexts {list(contexts)}; "
            f"{attn['kv_gather_bytes_avoided']} gathered-view bytes avoided"
        )
    except Exception as e:  # noqa: BLE001
        log(f"bench: caption_attention micro-bench failed ({e}); clips/s still valid")

    if caption:
        record["caption_output_tokens_per_sec"] = caption["value"]
        record["caption_config"] = caption_cfg
        if "caption_pipeline_efficiency" in caption:
            # SPEED_OF_LIGHT.md:67-81 — in-pipeline ÷ standalone tok/s on
            # identical requests through the same engine
            record["caption_pipeline_efficiency"] = caption["caption_pipeline_efficiency"]
            record["caption_pipeline_tokens_per_sec"] = caption["pipeline_tokens_per_sec"]
        # decomposition of the caption number: per-phase seconds (prep /
        # vision-encode / prefill / decode / idle) + shared-prefix KV cache
        # traffic for the in-pipeline pass
        if "pipeline_phases" in caption:
            record["caption_phase_breakdown"] = caption["pipeline_phases"]
        for key in (
            "prefill_tokens",
            "prefix_cache_hits",
            "prefix_tokens_saved",
        ):
            if f"pipeline_{key}" in caption:
                record[f"caption_{key}"] = caption[f"pipeline_{key}"]
        # paged-KV accounting: per-request reservation vs the slot-row
        # engine's worst-case lane row, and the copy-free prefix sharing
        # proof (block refs > 0 with zero whole-prefix copy dispatches)
        for key in (
            "kv_bytes_per_request",
            "kv_bytes_per_request_worst_case",
            "kv_block_size",
            "kv_block_size_requested",
            "kv_blocks_total",
            "kv_blocks_peak",
            "prefix_block_refs",
            "prefix_copy_dispatches",
            "kv_cow_copies",
            "paged_attention",
            "paged_kernel_steps",
            "kv_gather_bytes_avoided",
            "decode_attention_s",
        ):
            if key in caption:
                record[f"caption_{key}"] = caption[key]
        # cross-job continuous batching: two owners sharing one engine must
        # interleave decode steps (per-owner tokens ride along)
        if "cross_job" in caption:
            record["caption_cross_job"] = caption["cross_job"]
        if caption.get("backend") == "tpu":
            record["decode_mfu"] = caption.get("decode_mfu", 0.0)
        elif caption.get("backend") != backend:
            # a cross-backend caption number must be machine-detectable
            record["caption_backend"] = caption.get("backend")
    # the BENCH_r*.json tail row is a durable surface: scripts/bench_trend.py
    # validates rounds against the bench-row golden before comparing them
    from cosmos_curate_tpu.utils import schema_stamp

    schema_stamp.stamp(record, "bench-row")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
